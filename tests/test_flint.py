"""flint (tools/flint) — the TPU-tracing static analyzer — and the
recompile sentinel (flink_tpu/observe).

Covers: a failing fixture per rule (TRC01/TRC02/JIT01/REG01/REG02/
REG04/NAT01 and the r24 concurrency rules LCK01/LCK02/LCK03/SHM01), the
suppression protocol (reason mandatory), the clean-tree invariant
(flint exits 0 over flink_tpu/ at HEAD — the same gate tools/tier1.sh
runs), the --rule CLI filter + per-rule timings in the JSON report,
the sentinel's compile/transfer accounting, and the
slow-lane bookkeeping of the known-flaky unaligned-checkpoint timing
test (deflake follow-up)."""

import json
from pathlib import Path

import pytest

from tools.flint.core import Project, discover, run_checks

REPO_ROOT = Path(__file__).resolve().parents[1]


def run_fixture(tmp_path, files, select):
    """Write a throwaway mini-package and run the selected rules."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text, encoding="utf-8")
    project = Project(discover(["flink_tpu/"], tmp_path), tmp_path)
    return run_checks(project, select=select)


# ------------------------------------------------------------------- TRC01


class TestTRC01HostSync:
    FILES = {
        "flink_tpu/__init__.py": "",
        "flink_tpu/eng.py": (
            "import numpy as np\n"
            "\n"
            "class MeshWindowEngine:\n"
            "    def process_batch(self, batch):\n"
            "        out = self._gather_step(batch)\n"
            "        return [np.asarray(g) for g in out]\n"
        ),
    }

    def test_per_array_read_on_step_result_trips(self, tmp_path):
        active, _ = run_fixture(tmp_path, self.FILES, ["TRC01"])
        assert [v.rule for v in active] == ["TRC01"]
        assert "np.asarray" in active[0].message
        assert active[0].path == "flink_tpu/eng.py"

    def test_reachability_is_required(self, tmp_path):
        # same sync, but in a class/method no hot root reaches: clean
        files = dict(self.FILES)
        files["flink_tpu/eng.py"] = files["flink_tpu/eng.py"].replace(
            "MeshWindowEngine", "SomeColdHelper")
        active, _ = run_fixture(tmp_path, files, ["TRC01"])
        assert active == []

    def test_block_until_ready_trips_transitively(self, tmp_path):
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/eng.py": (
                "class MeshSessionEngine:\n"
                "    def on_watermark(self, wm):\n"
                "        self._drain()\n"
                "    def _drain(self):\n"
                "        self.fence.block_until_ready()\n"
            ),
        }
        active, _ = run_fixture(tmp_path, files, ["TRC01"])
        assert len(active) == 1
        assert "block_until_ready" in active[0].message

    def test_scalar_cast_of_device_value_trips(self, tmp_path):
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/eng.py": (
                "class SlotTable:\n"
                "    def fire(self, sm):\n"
                "        merged = self._fire_jit(self.accs, sm)\n"
                "        return int(merged[0])\n"
            ),
        }
        active, _ = run_fixture(tmp_path, files, ["TRC01"])
        assert len(active) == 1
        assert "int() on a device value" in active[0].message


# ------------------------------------------------------------------- TRC02


class TestTRC02TracerControlFlow:
    def test_if_on_jit_argument_trips(self, tmp_path):
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/k.py": (
                "import jax\n"
                "\n"
                "@jax.jit\n"
                "def step(x):\n"
                "    if x > 0:\n"
                "        return x\n"
                "    return -x\n"
            ),
        }
        active, _ = run_fixture(tmp_path, files, ["TRC02"])
        assert [v.rule for v in active] == ["TRC02"]
        assert "data-dependent" in active[0].message

    def test_shape_checks_are_trace_time_static(self, tmp_path):
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/k.py": (
                "import jax\n"
                "\n"
                "@jax.jit\n"
                "def step(x):\n"
                "    if x.shape[0] > 4:\n"
                "        return x[:4]\n"
                "    return x\n"
            ),
        }
        active, _ = run_fixture(tmp_path, files, ["TRC02"])
        assert active == []

    def test_while_on_derived_value_in_wrapped_fn(self, tmp_path):
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/k.py": (
                "import jax\n"
                "\n"
                "def body(x):\n"
                "    y = x * 2\n"
                "    while y < 10:\n"
                "        y = y + 1\n"
                "    return y\n"
                "\n"
                "stepped = jax.jit(body)\n"
            ),
        }
        active, _ = run_fixture(tmp_path, files, ["TRC02"])
        assert len(active) == 1
        assert "while" in active[0].message


# ------------------------------------------------------------------- JIT01


class TestJIT01UnstableIdentity:
    def test_jit_lambda_per_call_trips(self, tmp_path):
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/k.py": (
                "import jax\n"
                "\n"
                "def step(v):\n"
                "    return jax.jit(lambda a: a + 1)(v)\n"
            ),
        }
        active, _ = run_fixture(tmp_path, files, ["JIT01"])
        assert [v.rule for v in active] == ["JIT01"]
        assert "fresh jit identity" in active[0].message

    def test_jit_local_def_in_loop_trips(self, tmp_path):
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/k.py": (
                "import jax\n"
                "\n"
                "def build(xs):\n"
                "    out = []\n"
                "    for x in xs:\n"
                "        def k(a):\n"
                "            return a * 2\n"
                "        out.append(jax.jit(k)(x))\n"
                "    return out\n"
            ),
        }
        active, _ = run_fixture(tmp_path, files, ["JIT01"])
        assert len(active) == 1
        assert "loop" in active[0].message

    def test_module_level_and_cached_builders_pass(self, tmp_path):
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/k.py": (
                "import jax\n"
                "\n"
                "_FENCE = jax.jit(lambda a: a[:1])\n"
                "_JIT_CACHE = {}\n"
                "\n"
                "def make_fence(acc):\n"
                "    fn = _JIT_CACHE.get('fence')\n"
                "    if fn is None:\n"
                "        fn = jax.jit(lambda a: a[:1, :1])\n"
                "        _JIT_CACHE['fence'] = fn\n"
                "    return fn(acc)\n"
            ),
        }
        active, _ = run_fixture(tmp_path, files, ["JIT01"])
        assert active == []


# ------------------------------------------------------------------- REG01


class TestREG01FaultPointRegistry:
    FILES = {
        "flink_tpu/__init__.py": "",
        "flink_tpu/chaos/__init__.py": (
            'KNOWN_FAULT_POINTS = ("good.point", "stale.point")\n'
        ),
        "flink_tpu/mod.py": (
            "from flink_tpu.chaos import injection as chaos\n"
            "\n"
            "def f():\n"
            '    chaos.fault_point("good.point")\n'
            '    chaos.fault_point("typo.poimt")\n'
        ),
        "tests/__init__.py": "",
        "tests/test_x.py": (
            "from flink_tpu.chaos.injection import FaultRule\n"
            "\n"
            'R1 = FaultRule(pattern="good.*", nth=1)\n'
            'R2 = FaultRule(pattern="zzz.never", nth=1)\n'
        ),
    }

    def test_typos_stales_and_dead_patterns_trip(self, tmp_path):
        active, _ = run_fixture(tmp_path, self.FILES, ["REG01"])
        msgs = "\n".join(v.message for v in active)
        assert "'typo.poimt' is not in" in msgs
        assert "'stale.point' has no" in msgs
        assert "'zzz.never' matches no known fault point" in msgs
        assert len(active) == 3

    def test_clean_registry_passes(self, tmp_path):
        files = dict(self.FILES)
        files["flink_tpu/chaos/__init__.py"] = \
            'KNOWN_FAULT_POINTS = ("good.point", "typo.poimt")\n'
        files["tests/test_x.py"] = (
            "from flink_tpu.chaos.injection import FaultRule\n"
            'R1 = FaultRule(pattern="good.*", nth=1)\n'
        )
        active, _ = run_fixture(tmp_path, files, ["REG01"])
        assert active == []


# ------------------------------------------------------------------- REG02


class TestREG02MetricCounterRegistry:
    FILES = {
        "flink_tpu/__init__.py": "",
        "flink_tpu/state/__init__.py": "",
        "flink_tpu/state/paged_spill.py": (
            'COUNTER_NAMES = ("rows_ok",)\n'
        ),
        "flink_tpu/metrics/__init__.py": (
            'KNOWN_METRIC_GROUPS = ("good", "unproduced")\n'
        ),
        "flink_tpu/prod.py": (
            "def bump(counters, g):\n"
            '    counters["rows_ok"] += 1\n'
            '    counters["rows_typo"] += 1\n'
            '    g.add_group("good")\n'
            '    g.add_group("bogus")\n'
        ),
    }

    def test_counter_and_group_drift_trips(self, tmp_path):
        active, _ = run_fixture(tmp_path, self.FILES, ["REG02"])
        msgs = "\n".join(v.message for v in active)
        assert "'rows_typo' is not in" in msgs
        assert "'bogus' is not in" in msgs
        assert "'unproduced' has no add_group producer" in msgs
        assert len(active) == 3


# ------------------------------------------------------------------- REG04


class TestREG04ProgramFamilyRegistry:
    FILES = {
        "flink_tpu/__init__.py": "",
        "flink_tpu/stateplane/__init__.py": "",
        "flink_tpu/stateplane/families.py": (
            'KNOWN_PROGRAM_FAMILIES = ("gather", "stale-family")\n'
        ),
        "flink_tpu/mod.py": (
            "from flink_tpu.tenancy.program_cache import PROGRAM_CACHE\n"
            "\n"
            "def build(key, builder):\n"
            '    PROGRAM_CACHE.get_or_build("gather", key, builder)\n'
            '    PROGRAM_CACHE.get_or_build("gahter", key, builder)\n'
        ),
    }

    def test_typo_kind_and_stale_entry_trip(self, tmp_path):
        active, _ = run_fixture(tmp_path, self.FILES, ["REG04"])
        msgs = "\n".join(v.message for v in active)
        assert "'gahter' is not in" in msgs
        assert "'stale-family' has no" in msgs
        assert len(active) == 2
        # the typo points at the producing call site, not the registry
        typo = next(v for v in active if "gahter" in v.message)
        assert typo.path == "flink_tpu/mod.py"

    def test_clean_inventory_passes(self, tmp_path):
        files = dict(self.FILES)
        files["flink_tpu/stateplane/families.py"] = \
            'KNOWN_PROGRAM_FAMILIES = ("gather", "gahter")\n'
        active, _ = run_fixture(tmp_path, files, ["REG04"])
        assert active == []

    def test_missing_registry_tuple_is_a_violation(self, tmp_path):
        files = dict(self.FILES)
        files["flink_tpu/stateplane/families.py"] = "def helper():\n    pass\n"
        active, _ = run_fixture(tmp_path, files, ["REG04"])
        assert len(active) == 1
        assert "KNOWN_PROGRAM_FAMILIES" in active[0].message


# ------------------------------------------------------------------- NAT01


class TestNAT01NativeCtypesSignatures:
    FILES = {
        "flink_tpu/__init__.py": "",
        "flink_tpu/native/__init__.py": (
            'NATIVE_SYMBOL_PREFIXES = ("sm_", "sx_")\n'
            "\n"
            "def load_slotmap():\n"
            "    lib = _load()\n"
            "    lib.sm_good.restype = None\n"
            "    lib.sm_good.argtypes = []\n"
            "    lib.sm_partial.argtypes = []\n"  # restype missing
            "    return lib\n"
        ),
        "flink_tpu/user.py": (
            "def run(lib):\n"
            "    lib.sm_good()\n"
            "    lib.sm_partial()\n"
            "    lib.sx_undeclared(3)\n"  # no declaration at all
        ),
    }

    def test_missing_and_partial_signatures_trip(self, tmp_path):
        active, _ = run_fixture(tmp_path, self.FILES, ["NAT01"])
        msgs = "\n".join(v.message for v in active)
        assert "'sx_undeclared' is called without argtypes and restype" \
            in msgs
        assert "'sm_partial' is called without restype" in msgs
        assert "'sm_partial' declares ['argtypes'] but not restype" \
            in msgs
        assert "sm_good" not in msgs
        assert len(active) == 3

    def test_clean_declarations_pass(self, tmp_path):
        files = dict(self.FILES)
        files["flink_tpu/native/__init__.py"] = (
            'NATIVE_SYMBOL_PREFIXES = ("sm_", "sx_")\n'
            "def load_all():\n"
            "    lib = _load()\n"
            "    for s in ('sm_good', 'sm_partial', 'sx_undeclared'):\n"
            "        pass\n"
            "    lib.sm_good.restype = None\n"
            "    lib.sm_good.argtypes = []\n"
            "    lib.sm_partial.restype = None\n"
            "    lib.sm_partial.argtypes = []\n"
            "    lib.sx_undeclared.restype = None\n"
            "    lib.sx_undeclared.argtypes = []\n"
            "    return lib\n"
        )
        active, _ = run_fixture(tmp_path, files, ["NAT01"])
        assert active == []

    def test_missing_prefix_registry_is_a_violation(self, tmp_path):
        files = dict(self.FILES)
        files["flink_tpu/native/__init__.py"] = "def load():\n    pass\n"
        active, _ = run_fixture(tmp_path, files, ["NAT01"])
        assert len(active) == 1
        assert "NATIVE_SYMBOL_PREFIXES" in active[0].message

    def test_head_tree_is_clean_for_nat01(self, tmp_path):
        # the real package: every native symbol called anywhere has a
        # full ctypes signature in its loader (the codec_free restype
        # this rule caught on introduction stays fixed)
        project = Project(
            discover(["flink_tpu/"], REPO_ROOT), REPO_ROOT)
        active, _ = run_checks(project, select=["NAT01"])
        assert active == []


# ------------------------------------------------------------------- LCK01


class TestLCK01GuardedFieldDiscipline:
    FILES = {
        "flink_tpu/__init__.py": "",
        "flink_tpu/ledger.py": (
            "import threading\n"
            "\n"
            "class Ledger:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.count = 0\n"
            "\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self.count += 1\n"
            "\n"
            "    def bump_twice(self):\n"
            "        with self._lock:\n"
            "            self.count += 2\n"
            "\n"
            "    def peek(self):\n"
            "        return self.count\n"
        ),
    }

    def test_unguarded_read_of_majority_guarded_field_trips(
            self, tmp_path):
        active, _ = run_fixture(tmp_path, self.FILES, ["LCK01"])
        assert [v.rule for v in active] == ["LCK01"]
        assert "'self.count' is guarded by 'self._lock'" \
            in active[0].message
        assert "peek" in active[0].message

    def test_guarded_everywhere_is_clean(self, tmp_path):
        files = dict(self.FILES)
        files["flink_tpu/ledger.py"] = files[
            "flink_tpu/ledger.py"].replace(
            "    def peek(self):\n"
            "        return self.count\n",
            "    def peek(self):\n"
            "        with self._lock:\n"
            "            return self.count\n")
        active, _ = run_fixture(tmp_path, files, ["LCK01"])
        assert active == []

    def test_majority_tie_infers_no_guard(self, tmp_path):
        # 1 of 2 write sites hold the lock: no strict majority, no
        # inference, no violations — the rule must not guess
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/ledger.py": (
                "import threading\n"
                "\n"
                "class Half:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self.n = 0\n"
                "\n"
                "    def locked_write(self):\n"
                "        with self._lock:\n"
                "            self.n = 1\n"
                "\n"
                "    def bare_write(self):\n"
                "        self.n = 2\n"
            ),
        }
        active, _ = run_fixture(tmp_path, files, ["LCK01"])
        assert active == []

    def test_module_scope_globals_are_checked(self, tmp_path):
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/reg.py": (
                "import threading\n"
                "\n"
                "_lock = threading.Lock()\n"
                "_registry = {}\n"
                "\n"
                "def put(k, v):\n"
                "    with _lock:\n"
                "        _registry[k] = v\n"
                "\n"
                "def drop(k):\n"
                "    with _lock:\n"
                "        _registry.pop(k, None)\n"
                "\n"
                "def peek():\n"
                "    return sorted(_registry)\n"
            ),
        }
        active, _ = run_fixture(tmp_path, files, ["LCK01"])
        assert len(active) == 1
        assert "_registry" in active[0].message
        assert "peek" in active[0].message


# ------------------------------------------------------------------- LCK02


class TestLCK02LockOrderConsistency:
    FILES = {
        "flink_tpu/__init__.py": "",
        "flink_tpu/pipe.py": (
            "import threading\n"
            "\n"
            "class Pipeline:\n"
            "    def __init__(self):\n"
            "        self.a = threading.Lock()\n"
            "        self.b = threading.Lock()\n"
            "\n"
            "    def forward(self):\n"
            "        with self.a:\n"
            "            with self.b:\n"
            "                pass\n"
            "\n"
            "    def backward(self):\n"
            "        with self.b:\n"
            "            with self.a:\n"
            "                pass\n"
        ),
    }

    def test_ab_ba_cycle_trips_with_both_witnesses(self, tmp_path):
        active, _ = run_fixture(tmp_path, self.FILES, ["LCK02"])
        assert len(active) == 1
        msg = active[0].message
        assert "potential deadlock" in msg
        assert "Pipeline.a" in msg and "Pipeline.b" in msg
        # both legs of the cycle carry a witness site
        assert msg.count("pipe.py") >= 2

    def test_consistent_order_is_clean(self, tmp_path):
        files = dict(self.FILES)
        files["flink_tpu/pipe.py"] = files["flink_tpu/pipe.py"].replace(
            "    def backward(self):\n"
            "        with self.b:\n"
            "            with self.a:\n",
            "    def backward(self):\n"
            "        with self.a:\n"
            "            with self.b:\n")
        active, _ = run_fixture(tmp_path, files, ["LCK02"])
        assert active == []

    def test_cycle_through_a_call_edge_trips(self, tmp_path):
        # the b->a leg hides behind a method call under the held lock
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/pipe.py": (
                "import threading\n"
                "\n"
                "class Pipeline:\n"
                "    def __init__(self):\n"
                "        self.a = threading.Lock()\n"
                "        self.b = threading.Lock()\n"
                "\n"
                "    def forward(self):\n"
                "        with self.a:\n"
                "            with self.b:\n"
                "                pass\n"
                "\n"
                "    def drain(self):\n"
                "        with self.b:\n"
                "            self._grab_a()\n"
                "\n"
                "    def _grab_a(self):\n"
                "        with self.a:\n"
                "            pass\n"
            ),
        }
        active, _ = run_fixture(tmp_path, files, ["LCK02"])
        assert len(active) == 1
        assert "potential deadlock" in active[0].message


# ------------------------------------------------------------------- LCK03


class TestLCK03CheckThenAct:
    FILES = {
        "flink_tpu/__init__.py": "",
        "flink_tpu/reg.py": (
            "import threading\n"
            "\n"
            "class Registry:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = {}\n"
            "\n"
            "    def put_if_absent(self, k, v):\n"
            "        with self._lock:\n"
            "            missing = k not in self._items\n"
            "        if missing:\n"
            "            with self._lock:\n"
            "                self._items[k] = v\n"
        ),
    }

    def test_check_then_act_across_release_trips(self, tmp_path):
        active, _ = run_fixture(tmp_path, self.FILES, ["LCK03"])
        assert [v.rule for v in active] == ["LCK03"]
        assert "_items" in active[0].message
        assert "release" in active[0].message

    def test_recheck_under_second_hold_is_exempt(self, tmp_path):
        # the compare-and-restore / drain-loop idiom: the second region
        # RE-READS the field under its own hold before acting — clean
        files = dict(self.FILES)
        files["flink_tpu/reg.py"] = files["flink_tpu/reg.py"].replace(
            "        if missing:\n"
            "            with self._lock:\n"
            "                self._items[k] = v\n",
            "        if missing:\n"
            "            with self._lock:\n"
            "                if k not in self._items:\n"
            "                    self._items[k] = v\n")
        active, _ = run_fixture(tmp_path, files, ["LCK03"])
        assert active == []

    def test_single_hold_is_clean(self, tmp_path):
        files = dict(self.FILES)
        files["flink_tpu/reg.py"] = (
            "import threading\n"
            "\n"
            "class Registry:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = {}\n"
            "\n"
            "    def put_if_absent(self, k, v):\n"
            "        with self._lock:\n"
            "            if k not in self._items:\n"
            "                self._items[k] = v\n"
        )
        active, _ = run_fixture(tmp_path, files, ["LCK03"])
        assert active == []


# ------------------------------------------------------------------- SHM01


class TestSHM01AttachedHandleWriteDiscipline:
    NATIVE = (
        'NATIVE_SYMBOL_PREFIXES = ("hc_",)\n'
        'HOTCACHE_WRITER_SYMBOLS = ("hc_put_batch", "hc_drop")\n'
    )
    FILES = {
        "flink_tpu/__init__.py": "",
        "flink_tpu/native/__init__.py": NATIVE,
        "flink_tpu/fe.py": (
            "class FrontendClient:\n"
            "    def attach(self, lib, path):\n"
            "        self.ptr = lib.hc_attach(path)\n"
            "\n"
            "    def corrupt(self, lib):\n"
            "        lib.hc_put_batch(self.ptr)\n"
        ),
    }

    def test_writer_symbol_in_attach_scope_trips(self, tmp_path):
        active, _ = run_fixture(tmp_path, self.FILES, ["SHM01"])
        assert [v.rule for v in active] == ["SHM01"]
        assert "hc_put_batch" in active[0].message
        assert active[0].path == "flink_tpu/fe.py"

    def test_writer_in_owner_scope_is_clean(self, tmp_path):
        files = dict(self.FILES)
        files["flink_tpu/fe.py"] = (
            "class OwnerCache:\n"
            "    def prime(self, lib, ptr):\n"
            "        lib.hc_put_batch(ptr)\n"
        )
        active, _ = run_fixture(tmp_path, files, ["SHM01"])
        assert active == []

    def test_missing_writer_registry_is_a_violation(self, tmp_path):
        files = dict(self.FILES)
        files["flink_tpu/native/__init__.py"] = \
            'NATIVE_SYMBOL_PREFIXES = ("hc_",)\n'
        active, _ = run_fixture(tmp_path, files, ["SHM01"])
        assert any("HOTCACHE_WRITER_SYMBOLS" in v.message
                   for v in active)


# ------------------------------------------------------- conc suppressions


class TestConcSuppressions:
    def test_reasoned_lck01_suppression_silences(self, tmp_path):
        files = dict(TestLCK01GuardedFieldDiscipline.FILES)
        files["flink_tpu/ledger.py"] = files[
            "flink_tpu/ledger.py"].replace(
            "    def peek(self):\n"
            "        return self.count\n",
            "    def peek(self):\n"
            "        # flint: disable=LCK01 -- fixture: approximate "
            "gauge read\n"
            "        return self.count\n")
        active, suppressed = run_fixture(tmp_path, files,
                                         ["LCK01", "SUP01"])
        assert active == []
        assert len(suppressed) == 1
        assert suppressed[0].reason == "fixture: approximate gauge read"

    def test_bare_lck03_suppression_still_fails_sup01(self, tmp_path):
        files = dict(TestLCK03CheckThenAct.FILES)
        files["flink_tpu/reg.py"] = files["flink_tpu/reg.py"].replace(
            "        if missing:\n"
            "            with self._lock:\n",
            "        if missing:\n"
            "            # flint: disable=LCK03\n"
            "            with self._lock:\n")
        active, suppressed = run_fixture(tmp_path, files,
                                         ["LCK03", "SUP01"])
        assert [v.rule for v in active] == ["SUP01"]
        assert "without a reason" in active[0].message
        assert len(suppressed) == 1


# ------------------------------------------------------------- suppressions


class TestSuppressions:
    BAD = (
        "import numpy as np\n"
        "\n"
        "class MeshWindowEngine:\n"
        "    def process_batch(self, batch):\n"
        "        out = self._gather_step(batch)\n"
        "{directive}"
        "        return [np.asarray(g) for g in out]\n"
    )

    def test_reasoned_suppression_silences(self, tmp_path):
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/eng.py": self.BAD.format(directive=(
                "        # flint: disable=TRC01 -- fixture: deliberate\n"
            )),
        }
        active, suppressed = run_fixture(tmp_path, files,
                                         ["TRC01", "SUP01"])
        assert active == []
        assert len(suppressed) == 1
        assert suppressed[0].reason == "fixture: deliberate"

    def test_suppression_without_reason_is_a_violation(self, tmp_path):
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/eng.py": self.BAD.format(directive=(
                "        # flint: disable=TRC01\n"
            )),
        }
        active, suppressed = run_fixture(tmp_path, files,
                                         ["TRC01", "SUP01"])
        assert [v.rule for v in active] == ["SUP01"]
        assert "without a reason" in active[0].message
        assert len(suppressed) == 1  # suppressed, but the gate still fails

    def test_unknown_rule_in_directive_is_flagged(self, tmp_path):
        files = {
            "flink_tpu/__init__.py": "",
            "flink_tpu/eng.py": (
                "x = 1  # flint: disable=NOPE99 -- misguided\n"
            ),
        }
        active, _ = run_fixture(tmp_path, files, ["SUP01"])
        assert [v.rule for v in active] == ["SUP01"]
        assert "unknown rule" in active[0].message


# --------------------------------------------------------------- clean tree


class TestCleanTree:
    def test_flint_exits_zero_on_head(self, tmp_path):
        """The acceptance invariant tier-1 enforces: the real package is
        flint-clean and every suppression carries a reason."""
        from tools.flint.cli import main

        report = tmp_path / "flint_report.json"
        rc = main([str(REPO_ROOT / "flink_tpu"), "--json", str(report)])
        data = json.loads(report.read_text())
        assert rc == 0, data["violations"]
        assert data["violations"] == []
        assert {"TRC01", "TRC02", "JIT01", "REG01", "REG02", "REG04",
                "LCK01", "LCK02", "LCK03", "SHM01"} <= set(data["rules"])
        for s in data["suppressed"]:
            assert s["reason"], f"reasonless suppression: {s}"

    def test_rule_filter_and_per_rule_timings(self, tmp_path):
        """--rule runs only the named rules and the JSON report carries
        their wall time (the tier-1 guard on conc-rule cost bloat)."""
        from tools.flint.cli import main

        pkg = tmp_path / "flink_tpu"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "eng.py").write_text(
            "import numpy as np\n"
            "import threading\n"
            "\n"
            "class MeshWindowEngine:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.n = 0\n"
            "    def process_batch(self, batch):\n"
            "        out = self._gather_step(batch)\n"
            "        return [np.asarray(g) for g in out]\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self.n += 1\n"
            "    def bump2(self):\n"
            "        with self._lock:\n"
            "            self.n += 1\n"
            "    def peek(self):\n"
            "        return self.n\n", encoding="utf-8")
        report = tmp_path / "r.json"
        # only LCK01 selected: the TRC01 host sync must NOT surface
        rc = main([str(pkg), "--rule", "LCK01", "--json", str(report)])
        assert rc == 1
        data = json.loads(report.read_text())
        assert {v["rule"] for v in data["violations"]} == {"LCK01"}
        assert set(data["rule_times_s"]) == {"LCK01"}
        assert all(t >= 0 for t in data["rule_times_s"].values())
        # repeatable + combines: both rules now surface
        rc = main([str(pkg), "--rule", "LCK01", "--rule", "TRC01",
                   "--json", str(report)])
        assert rc == 1
        data = json.loads(report.read_text())
        assert {v["rule"] for v in data["violations"]} == \
            {"LCK01", "TRC01"}
        assert set(data["rule_times_s"]) == {"LCK01", "TRC01"}

    def test_unknown_rule_flag_is_a_usage_error(self, capsys):
        from tools.flint.cli import main

        rc = main([str(REPO_ROOT / "flink_tpu"), "--rule", "NOPE99"])
        assert rc == 2
        assert "unknown rule" in capsys.readouterr().err

    def test_nonexistent_target_is_a_usage_error(self, capsys):
        """A typo'd path must exit 2 with a diagnostic, not traceback."""
        from tools.flint.cli import main

        rc = main([str(REPO_ROOT / "flink_tpu" / "nonexistent.py")])
        assert rc == 2
        assert "no such file" in capsys.readouterr().err

    def test_known_fault_points_matches_runtime_registry(self):
        """flint parses the tuple statically; the import path must agree."""
        import ast

        from flink_tpu.chaos import KNOWN_FAULT_POINTS

        src = (REPO_ROOT / "flink_tpu/chaos/__init__.py").read_text()
        tree = ast.parse(src)
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    getattr(t, "id", None) == "KNOWN_FAULT_POINTS"
                    for t in node.targets):
                parsed = tuple(e.value for e in node.value.elts)
                assert parsed == KNOWN_FAULT_POINTS
                return
        pytest.fail("KNOWN_FAULT_POINTS literal not found")


# ----------------------------------------------------------- the sentinel


class TestRecompileSentinel:
    def test_counts_fresh_compile_and_passes_cache_hits(self):
        import jax
        import jax.numpy as jnp

        from flink_tpu.observe import RecompileSentinel

        with RecompileSentinel(max_compiles=None) as warm:
            f = jax.jit(lambda x: x * 3 + 1)
            f(jnp.ones(17))
        assert warm.compiles >= 1  # fresh identity + shape => compiled
        with RecompileSentinel(max_compiles=0, label="steady") as s:
            f(jnp.ones(17))  # cache hit: same identity, same shape
        assert s.compiles == 0

    def test_raises_on_budget_violation(self):
        import jax
        import jax.numpy as jnp

        from flink_tpu.observe import (
            RecompileSentinel,
            SteadyStateViolation,
        )

        with pytest.raises(SteadyStateViolation, match="jit identity"):
            with RecompileSentinel(max_compiles=0, label="fixture"):
                jax.jit(lambda x: x - 7)(jnp.ones(9))

    def test_transfer_budget(self):
        import jax
        import jax.numpy as jnp

        from flink_tpu.observe import (
            RecompileSentinel,
            SteadyStateViolation,
        )

        x = jnp.arange(8)
        with RecompileSentinel(max_compiles=None) as s:
            jax.device_get(x)
        assert s.transfers >= 1
        with pytest.raises(SteadyStateViolation, match="transfer"):
            with RecompileSentinel(max_compiles=None, max_transfers=0):
                jax.device_get(x)

    def test_never_masks_region_exception(self):
        from flink_tpu.observe import RecompileSentinel

        with pytest.raises(ValueError, match="inner"):
            with RecompileSentinel(max_compiles=0):
                raise ValueError("inner")


# --------------------------------------------- deflake bookkeeping (satellite)


class TestSlowLaneBookkeeping:
    def test_unaligned_timing_test_stays_in_slow_lane(self):
        """The known-flaky wall-clock assertion must keep its slow
        marker, keep the justification comment explaining WHY, and the
        tier-1 gate must keep excluding the slow lane."""
        src = (REPO_ROOT / "tests/test_unaligned_checkpoint.py") \
            .read_text()
        i_mark = src.index("@pytest.mark.slow")
        i_test = src.index("def test_barrier_overtakes_backlog")
        assert i_mark < i_test, "slow marker must precede the timing test"
        justification = src[:i_mark]
        assert "WALL-CLOCK" in justification and "flaked" in justification, \
            "the slow marker lost its justification comment"
        tier1 = (REPO_ROOT / "tools/tier1.sh").read_text()
        assert "not slow" in tier1, "tier-1 no longer excludes slow tests"

    def test_slow_marker_is_registered(self):
        src = (REPO_ROOT / "tests/conftest.py").read_text()
        assert '"markers"' in src and "slow:" in src
