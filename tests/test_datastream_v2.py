"""DataStream V2 API facade.

reference: flink-datastream-api — partitioning as stream types,
``process`` everywhere, two-output functions, connectAndProcess,
broadcast — mapped onto the same engine as V1.
"""

import numpy as np

from flink_tpu import Configuration
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.datastream.v2 import (
    ExecutionEnvironment,
    OneInputStreamProcessFunction,
    TwoInputBroadcastStreamProcessFunction,
    TwoInputNonBroadcastStreamProcessFunction,
    TwoOutputStreamProcessFunction,
)
from flink_tpu.state.keyed_state import ReducingStateDescriptor


def _env():
    return ExecutionEnvironment.get_instance(Configuration({
        "execution.micro-batch.size": 4096}))


def _src(n=20_000, keys=50):
    return DataGenSource(total_records=n, num_keys=keys,
                         events_per_second_of_eventtime=10_000)


class Doubler(OneInputStreamProcessFunction):
    def process_batch(self, batch, out, ctx):
        out.collect(batch.with_column(
            "value", np.asarray(batch["value"]) * 2))


class KeyedCounter(OneInputStreamProcessFunction):
    """Counts per key with keyed state + an event-time timer through
    the V2 context."""

    def open(self, ctx):
        self.desc = ReducingStateDescriptor("n", np.add, np.int64, 0)

    def process_batch(self, batch, out, ctx):
        keys = batch[KEY_ID_FIELD]
        ctx.state(self.desc).add(keys, np.ones(len(keys), dtype=np.int64))
        ctx.timer_service().register_event_time_timers(
            keys, np.full(len(keys), 1 << 50))

    def on_timer(self, key_ids, timestamps, out, ctx):
        counts = ctx.state(self.desc).get(key_ids)
        out.collect(RecordBatch({KEY_ID_FIELD: key_ids,
                                 "count": counts}))


def test_process_and_keyed_state_end_to_end():
    env = _env()
    sink = CollectSink()
    (env.from_source(_src())
        .process(Doubler())
        .key_by("key")
        .process(KeyedCounter())
        .to_sink(sink))
    env.execute("v2-counts")
    b = sink.result()
    got = dict(zip(b[KEY_ID_FIELD].tolist(), b["count"].tolist()))
    assert len(got) == 50
    assert sum(got.values()) == 20_000


class Splitter(TwoOutputStreamProcessFunction):
    """Evens to output 1, odds to output 2 — V2's typed second output."""

    def process_batch(self, batch, out1, out2, ctx):
        v = np.asarray(batch["key"])
        out1.collect(batch.filter(v % 2 == 0))
        out2.collect(batch.filter(v % 2 == 1))


def test_two_output_process_function():
    env = _env()
    evens, odds = CollectSink(), CollectSink()
    main, side = env.from_source(_src(n=8000)).process_two_output(
        Splitter())
    main.to_sink(evens)
    side.to_sink(odds)
    env.execute("v2-split")
    e = evens.result()["key"]
    o = odds.result()["key"]
    assert len(e) + len(o) == 8000
    assert (np.asarray(e) % 2 == 0).all()
    assert (np.asarray(o) % 2 == 1).all()


class Zipper(TwoInputNonBroadcastStreamProcessFunction):
    def open(self, ctx):
        self.seen = {"first": 0, "second": 0}

    def process_batch_first(self, batch, out, ctx):
        self.seen["first"] += len(batch)
        out.collect(batch.with_column("side", np.zeros(len(batch))))

    def process_batch_second(self, batch, out, ctx):
        self.seen["second"] += len(batch)
        out.collect(batch.with_column("side", np.ones(len(batch))))


def test_connect_and_process_two_inputs():
    env = _env()
    sink = CollectSink()
    a = env.from_source(_src(n=5000))
    b = env.from_source(_src(n=3000))
    a.connect_and_process(b, Zipper()).to_sink(sink)
    env.execute("v2-connect")
    sides = np.asarray(sink.result()["side"])
    assert (sides == 0).sum() == 5000
    assert (sides == 1).sum() == 3000


class Enricher(TwoInputBroadcastStreamProcessFunction):
    """Broadcast side fills a dimension map; data side joins it."""

    def process_broadcast_batch(self, batch, out, ctx, bstate):
        for k, v in zip(batch["key"].tolist(), batch["value"].tolist()):
            bstate[int(k) % 10] = v

    def process_batch(self, batch, out, ctx, bstate):
        dims = np.asarray([bstate.get(int(k) % 10, -1.0)
                           for k in batch["key"].tolist()])
        out.collect(batch.with_column("dim", dims))


def test_broadcast_connect():
    env = _env()
    sink = CollectSink()
    data = env.from_source(_src(n=4000))
    dim = env.from_source(_src(n=1000)).broadcast()
    data.connect_and_process(dim, Enricher()).to_sink(sink)
    env.execute("v2-broadcast")
    b = sink.result()
    assert len(b) == 4000
    assert "dim" in b.columns


def test_non_keyed_context_rejects_state_and_timers():
    import pytest

    class Bad(OneInputStreamProcessFunction):
        def process_batch(self, batch, out, ctx):
            ctx.state(ReducingStateDescriptor("n", np.add))

    env = _env()
    sink = CollectSink()
    env.from_source(_src(n=1000)).process(Bad()).to_sink(sink)
    with pytest.raises(RuntimeError, match="KeyedPartitionStream"):
        env.execute("v2-bad")


def test_windows_on_keyed_streams():
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    env = _env()
    sink = CollectSink()
    (env.from_source(_src(n=10_000))
        .key_by("key")
        .window(TumblingEventTimeWindows.of(1000))
        .sum("value").sink_to(sink))
    env.execute("v2-windows")
    assert len(sink.result()) > 0


class KeyedSplitCounter(TwoOutputStreamProcessFunction):
    """Two-output on a KEYED stream using keyed state (review repro)."""

    def open(self, ctx):
        self.desc = ReducingStateDescriptor("n", np.add, np.int64, 0)

    def process_batch(self, batch, out1, out2, ctx):
        keys = batch[KEY_ID_FIELD]
        ctx.state(self.desc).add(keys, np.ones(len(keys), dtype=np.int64))
        counts = ctx.state(self.desc).get(keys)
        out1.collect(batch.filter(counts % 2 == 1))
        out2.collect(batch.filter(counts % 2 == 0))


def test_keyed_two_output_with_state():
    env = _env()
    s1, s2 = CollectSink(), CollectSink()
    main, side = env.from_source(_src(n=6000)).key_by("key") \
        .process_two_output(KeyedSplitCounter())
    main.to_sink(s1)
    side.to_sink(s2)
    env.execute("v2-keyed-split")
    assert len(s1.result()) + len(s2.result()) == 6000
    assert len(s1.result()) > 0 and len(s2.result()) > 0


class KeyedZip(TwoInputNonBroadcastStreamProcessFunction):
    """Keyed connect: per-key tallies from both inputs (review repro)."""

    def open(self, ctx):
        self.desc = ReducingStateDescriptor("n", np.add, np.int64, 0)

    def process_batch_first(self, batch, out, ctx):
        keys = batch[KEY_ID_FIELD]
        ctx.state(self.desc).add(keys, np.ones(len(keys), dtype=np.int64))

    def process_batch_second(self, batch, out, ctx):
        keys = batch[KEY_ID_FIELD]
        ctx.state(self.desc).add(keys, np.ones(len(keys), dtype=np.int64))
        counts = ctx.state(self.desc).get(keys)
        out.collect(batch.with_column("tally", counts))


def test_keyed_connect_and_process_shares_state():
    env = _env()
    sink = CollectSink()
    a = env.from_source(_src(n=4000)).key_by("key")
    b = env.from_source(_src(n=4000)).key_by("key")
    a.connect_and_process(b, KeyedZip()).to_sink(sink)
    env.execute("v2-keyed-connect")
    out = sink.result()
    assert len(out) == 4000
    # tallies grow past 1: both inputs fold into ONE keyed state
    assert int(np.asarray(out["tally"]).max()) > 1


def test_mixed_keyedness_connect_rejected():
    import pytest

    env = _env()
    a = env.from_source(_src(n=100)).key_by("key")
    b = env.from_source(_src(n=100))
    with pytest.raises(TypeError, match="both streams keyed"):
        a.connect_and_process(b, KeyedZip())


def test_keyed_process_rejects_two_output_function():
    import pytest

    env = _env()
    with pytest.raises(TypeError, match="process_two_output"):
        env.from_source(_src(n=100)).key_by("key").process(
            KeyedSplitCounter())


def test_from_source_name_reaches_the_graph():
    env = _env()
    sink = CollectSink()
    env.from_source(_src(n=100), name="orders").process(
        Doubler()).to_sink(sink)
    names = [t.name for t in env._env.transformations] \
        if hasattr(env._env, "transformations") else []
    r = env.execute("v2-named")
    ops = r.metrics.get("per_operator", {})
    assert any("orders" in k for k in ops), ops
