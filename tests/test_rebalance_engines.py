"""Live key-group rebalancing on the mesh engines, pinned to oracles.

The moves happen MID-STREAM with state live and paged spill under
forced eviction (1024 device slots vs thousands of live keys), and
every run must stay row-for-row identical to the never-rebalanced
single-device windower: the assignment table is pure routing — WHERE
state lives — and must never change WHAT is computed. Also pinned:
sharded checkpoints under a non-contiguous layout (one unit per
same-shard run) merge back losslessly and restore contiguous, a
subsequent reshard() resets the table, and the SkewResponder closes
the detect -> rebalance -> split loop end-to-end on a skewed stream.
"""

import numpy as np
import pytest

from flink_tpu.autoscale import RebalancePolicy, SkewResponder
from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.parallel.load import ShardLoadAccountant
from flink_tpu.parallel.mesh import make_mesh
from flink_tpu.state.keygroups import KeyGroupAssignment
from flink_tpu.windowing.aggregates import SumAggregate
from flink_tpu.windowing.assigners import TumblingEventTimeWindows
from flink_tpu.windowing.sessions import SessionWindower
from flink_tpu.windowing.windower import SliceSharedWindower

GAP = 100


def keyed_batch(keys, vals, ts):
    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: np.asarray(keys, dtype=np.int64),
         "v": np.asarray(vals, dtype=np.float32)},
        timestamps=np.asarray(ts, dtype=np.int64))


def _stream(num_keys=6_000, n_steps=8, per_step=2_500, seed=31,
            hot_frac=0.0, hot_key=7):
    """Optionally skewed: ``hot_frac`` of each step's records carry one
    key. Values are integer-valued float32 so float sums stay exact —
    bit-identity assertions remain meaningful through salting."""
    rng = np.random.default_rng(seed)
    steps = []
    for s in range(n_steps):
        keys = rng.integers(0, num_keys, per_step).astype(np.int64)
        if hot_frac:
            hot = rng.random(per_step) < hot_frac
            keys[hot] = hot_key
        vals = rng.integers(1, 6, per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        steps.append((keys, vals, ts, (s - 1) * 80))
    return steps


def _run(engine, steps, rebalances=None, on_step=None):
    """Drive steps; rebalances = {step index -> fn(engine) -> assignment}
    applied BEFORE that step (mid-stream, state live)."""
    fired = []
    for i, (keys, vals, ts, wm) in enumerate(steps):
        if rebalances and i in rebalances:
            rep = engine.reassign_key_groups(rebalances[i](engine))
            assert rep["groups_moved"] > 0 and rep["rows_moved"] > 0
        engine.process_batch(keyed_batch(keys, vals, ts))
        fired.extend(engine.on_watermark(wm))
        if on_step is not None:
            on_step(i, keys)
    fired.extend(engine.on_watermark(1 << 60))
    out = {}
    for b in fired:
        for r in b.to_rows():
            out[(r[KEY_ID_FIELD], r["window_start"],
                 r["window_end"])] = r["sum_v"]
    return out


def _assert_equal(got, expected):
    assert len(expected) > 0
    assert set(got) == set(expected)
    for k in expected:
        assert got[k] == pytest.approx(expected[k], rel=1e-4,
                                       abs=1e-3), k


def _session_engine(mesh, **kw):
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

    return MeshSessionEngine(GAP, SumAggregate("v"), mesh,
                             capacity_per_shard=1 << 14, **kw)


def _window_engine(mesh, **kw):
    from flink_tpu.parallel.sharded_windower import MeshWindowEngine

    return MeshWindowEngine(TumblingEventTimeWindows.of(100),
                            SumAggregate("v"), mesh,
                            capacity_per_shard=1 << 14, **kw)


def _move_half_of_shard(src, dst):
    """fn(engine) -> assignment moving half of ``src``'s groups to
    ``dst`` — derived from the engine's CURRENT table so two moves
    compose."""
    def fn(engine):
        cur = engine.key_group_assignment
        groups = cur.groups_of_shard(src)
        assert len(groups) > 1
        return cur.move(groups[: len(groups) // 2], dst)
    return fn


# ---------------------------------------------------------------------------
# mid-stream moves: oracle equivalence under forced paged eviction
# ---------------------------------------------------------------------------


class TestRebalanceOracle:
    def test_session_engine_two_moves_paged(self):
        """Two composed mid-stream rebalances (4-shard mesh, 1024
        device slots vs ~6k live sessions: resident AND paged rows
        move), bit-identical to the single-device oracle."""
        steps = _stream()
        eng = _session_engine(make_mesh(4), max_device_slots=1024)
        oracle = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)
        got = _run(eng, steps, rebalances={
            3: _move_half_of_shard(0, 2),
            6: _move_half_of_shard(1, 3),
        })
        _assert_equal(got, _run(oracle, steps))
        assert eng.rebalances_completed == 2
        assert not eng.key_group_assignment.is_contiguous
        # the non-contiguous layout decomposes into more runs than
        # shards — the checkpoint-unit granularity follows the table
        assert len(eng.shard_key_group_runs()) > eng.P
        assert eng.last_rebalance["rows_moved"] > 0
        c = eng.spill_counters()
        assert c["pages_evicted"] > 0 and c["pages_reloaded"] > 0

    def test_window_engine_two_moves(self):
        steps = _stream(seed=43)
        eng = _window_engine(make_mesh(4), max_device_slots=1024)
        oracle = SliceSharedWindower(TumblingEventTimeWindows.of(100),
                                     SumAggregate("v"), capacity=1 << 15)
        got = _run(eng, steps, rebalances={
            2: _move_half_of_shard(0, 3),
            5: _move_half_of_shard(2, 1),
        })
        _assert_equal(got, _run(oracle, steps))
        assert eng.rebalances_completed == 2
        assert not eng.key_group_assignment.is_contiguous

    def test_reshard_after_rebalance_resets_to_contiguous(self):
        """reshard() changes P: the old table is meaningless for the
        new shard count, so the handoff re-routes by the contiguous
        formula — and the stream still matches the oracle."""
        steps = _stream(seed=57)
        eng = _session_engine(make_mesh(4), max_device_slots=1024)
        oracle = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)
        fired = []
        for i, (keys, vals, ts, wm) in enumerate(steps):
            if i == 2:
                eng.reassign_key_groups(
                    _move_half_of_shard(0, 2)(eng))
                assert not eng.key_group_assignment.is_contiguous
            if i == 5:
                eng.reshard(8)
                assert eng.key_group_assignment.is_contiguous
            eng.process_batch(keyed_batch(keys, vals, ts))
            fired.extend(eng.on_watermark(wm))
        fired.extend(eng.on_watermark(1 << 60))
        got = {}
        for b in fired:
            for r in b.to_rows():
                got[(r[KEY_ID_FIELD], r["window_start"],
                     r["window_end"])] = r["sum_v"]
        _assert_equal(got, _run(oracle, steps))
        assert eng.P == 8

    def test_noop_and_validation(self):
        eng = _session_engine(make_mesh(4))
        cur = eng.key_group_assignment
        rep = eng.reassign_key_groups(cur)  # identical table: no-op
        assert rep["groups_moved"] == 0 and rep.get("noop")
        assert eng.rebalances_completed == 0
        with pytest.raises(TypeError):
            eng.reassign_key_groups("not-an-assignment")
        with pytest.raises(ValueError):
            # rebalance moves groups; changing P is reshard()'s job
            eng.reassign_key_groups(
                KeyGroupAssignment.contiguous(8, eng.max_parallelism))

    def test_partial_failover_refused_under_live_assignment(self):
        """A dead shard's groups are no longer one contiguous range
        under a live table — the bounded-replay contract is gone, so
        lose_shards must refuse (whole-job restore applies)."""
        steps = _stream(n_steps=2)
        eng = _session_engine(make_mesh(4), max_device_slots=1024)
        for keys, vals, ts, wm in steps:
            eng.process_batch(keyed_batch(keys, vals, ts))
        eng.reassign_key_groups(_move_half_of_shard(0, 2)(eng))
        with pytest.raises(ValueError, match="rebalanced"):
            eng.lose_shard(1)
        with pytest.raises(ValueError, match="non-contiguous"):
            eng.shard_key_groups()


# ---------------------------------------------------------------------------
# sharded checkpoints under a non-contiguous table
# ---------------------------------------------------------------------------


class TestRebalancedCheckpointRoundTrip:
    def test_units_follow_runs_merge_and_restore_contiguous(self):
        """Mid-stream: rebalance, snapshot per-unit (one unit per
        same-shard RUN), merge, restore into a FRESH engine — which
        comes back on the contiguous layout (the assignment is runtime
        routing state, never checkpointed) — and both the original and
        the restored engine finish the stream oracle-identical."""
        steps = _stream(seed=71, n_steps=8)
        eng = _session_engine(make_mesh(4), max_device_slots=1024)
        oracle = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)
        cut = 5
        fired = []
        for i, (keys, vals, ts, wm) in enumerate(steps[:cut]):
            if i == 3:
                eng.reassign_key_groups(_move_half_of_shard(1, 3)(eng))
            eng.process_batch(keyed_batch(keys, vals, ts))
            fired.extend(eng.on_watermark(wm))
        # unit keys are the maximal same-shard runs of the LIVE table
        units = eng.snapshot_sharded(mode="savepoint")
        runs = eng.shard_key_group_runs()
        assert set(units) == {(g0, g1) for g0, g1, _p in runs}
        assert len(units) > eng.P  # non-contiguous: more runs than shards
        merged = eng.merge_unit_snapshots(list(units.values()))
        # restored engine: contiguous routing, same logical state
        fresh = _session_engine(make_mesh(4), max_device_slots=1024)
        fresh.restore(merged)
        assert fresh.key_group_assignment.is_contiguous
        fresh_fired = list(fired)
        for eng2, acc in ((eng, fired), (fresh, fresh_fired)):
            for keys, vals, ts, wm in steps[cut:]:
                eng2.process_batch(keyed_batch(keys, vals, ts))
                acc.extend(eng2.on_watermark(wm))
            acc.extend(eng2.on_watermark(1 << 60))

        def to_map(batches):
            out = {}
            for b in batches:
                for r in b.to_rows():
                    out[(r[KEY_ID_FIELD], r["window_start"],
                         r["window_end"])] = r["sum_v"]
            return out

        expected = _run(oracle, steps)
        _assert_equal(to_map(fired), expected)
        _assert_equal(to_map(fresh_fired), expected)


# ---------------------------------------------------------------------------
# SkewResponder: the loop closed end-to-end on a live engine
# ---------------------------------------------------------------------------


class TestSkewResponderEndToEnd:
    def test_detect_rebalance_split_on_skewed_stream(self):
        """40% of all records carry ONE key: the accountant detects it,
        the policy plans moves AND flags the dominant key, the
        responder applies both to the live engine — and the output is
        still bit-identical to the oracle (integer-valued float sums
        stay exact through salting)."""
        class Clock:
            t = 0.0

            def __call__(self):
                return self.t

        clk = Clock()
        steps = _stream(seed=83, hot_frac=0.4, hot_key=7)
        eng = _session_engine(make_mesh(4), max_device_slots=1024)
        acc = ShardLoadAccountant(eng.P, eng.max_parallelism,
                                  ewma_alpha=0.5, clock=clk)
        resp = SkewResponder(
            eng, acc,
            policy=RebalancePolicy(imbalance_trigger=1.3, hysteresis=0.02,
                                   cooldown_s=0.0, clock=clk),
            salts=8, hot_key_share=0.5, allow_inexact=True)

        def on_step(_i, keys):
            clk.t += 1.0
            resp.note_batch(keys)
            acc.tick()
            resp.maybe_respond(now=clk.t)

        got = _run(eng, steps, on_step=on_step)
        oracle = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)
        _assert_equal(got, _run(oracle, steps))
        # every stage of the ladder actually fired
        assert resp.rebalances >= 1 and resp.groups_moved >= 1
        assert resp.keys_split >= 1 and 7 in eng._hot_keys
        stats = eng.hot_key_stats()
        assert stats["salted_records"] > 0 and stats["salted_fires"] > 0
        assert eng.rebalances_completed == resp.rebalances
