"""Adaptive scheduler, HA leader election, job graph store, blob store.

reference test models: scheduler/adaptive tests (WaitingForResources /
Executing transitions), leaderelection tests, Dispatcher HA recovery
ITCases, BlobServer tests.
"""

import os
import time

import numpy as np
import pytest

from flink_tpu.cluster.ha import (
    BlobStore,
    FileLeaderElectionDriver,
    JobGraphStore,
    LeaderContender,
    LeaderElectionService,
)
from flink_tpu.cluster.minicluster import (
    FAILED,
    FINISHED,
    RUNNING,
    WAITING_FOR_RESOURCES,
    MiniCluster,
)
from flink_tpu.connectors.sinks import JsonLinesFileSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.config import Configuration
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


class SlowDataGen(DataGenSource):
    def poll_batch(self, max_records):
        b = super().poll_batch(max_records)
        if b is not None:
            time.sleep(0.01)
        return b


def build(env, out_path, total=4_000, source_cls=DataGenSource):
    (env.add_source(source_cls(total_records=total, num_keys=5,
                               events_per_second_of_eventtime=4000),
                    WatermarkStrategy.for_bounded_out_of_orderness(0))
     .key_by("key").window(TumblingEventTimeWindows.of(500)).count()
     .sink_to(JsonLinesFileSink(out_path)))


class TestAdaptiveScheduler:
    def test_default_mode_fails_fast_without_slots(self, tmp_path):
        cluster = MiniCluster(Configuration(
            {"rest.port": -1, "cluster.task-executors": 0}))
        try:
            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 512}))
            build(env, str(tmp_path / "o.jsonl"))
            client = cluster.submit(env, "nores")
            st = client.wait(timeout=20)
            assert st["status"] == FAILED
            assert "no slots" in st["error"]
        finally:
            cluster.shutdown()

    def test_adaptive_waits_for_resources_then_runs(self, tmp_path):
        cluster = MiniCluster(Configuration(
            {"rest.port": -1, "cluster.task-executors": 0}))
        try:
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 512,
                "jobmanager.scheduler": "adaptive",
            }))
            build(env, str(tmp_path / "o.jsonl"))
            client = cluster.submit(env, "adaptive-wait")
            # the job parks in WaitingForResources instead of failing
            deadline = time.monotonic() + 5
            seen_waiting = False
            while time.monotonic() < deadline:
                if client.status()["status"] == WAITING_FOR_RESOURCES:
                    seen_waiting = True
                    break
                time.sleep(0.02)
            assert seen_waiting
            cluster.add_task_executor()  # resources arrive
            st = client.wait(timeout=30)
            assert st["status"] == FINISHED
            states = [h["state"] for h in st["state_history"]]
            assert states[:1] == ["CREATED"]
            assert WAITING_FOR_RESOURCES in states and RUNNING in states
        finally:
            cluster.shutdown()

    def test_adaptive_wait_timeout_fails(self, tmp_path):
        cluster = MiniCluster(Configuration(
            {"rest.port": -1, "cluster.task-executors": 0}))
        try:
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 512,
                "jobmanager.scheduler": "adaptive",
                "jobmanager.adaptive-scheduler.resource-wait-timeout-ms":
                    300,
            }))
            build(env, str(tmp_path / "o.jsonl"))
            client = cluster.submit(env, "adaptive-timeout")
            st = client.wait(timeout=20)
            assert st["status"] == FAILED
            assert "resource wait timeout" in st["error"]
        finally:
            cluster.shutdown()

    def test_adaptive_rescales_on_new_resources(self, tmp_path):
        """A running adaptive job redeploys (from its checkpoint) when the
        resource picture changes — and still produces exactly-once totals
        (reference: reactive mode rescale)."""
        ck = str(tmp_path / "ck")
        out = str(tmp_path / "o.jsonl")
        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 256,
                "jobmanager.scheduler": "adaptive",
                "state.checkpoints.dir": ck,
                "execution.checkpointing.every-n-source-batches": 2,
            }))
            build(env, out, total=40_000, source_cls=SlowDataGen)
            client = cluster.submit(env, "adaptive-rescale")
            # wait until running, then add an executor -> reactive restart
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if client.status()["status"] == RUNNING:
                    break
                time.sleep(0.02)
            time.sleep(0.3)  # let some checkpoints land
            cluster.add_task_executor()
            st = client.wait(timeout=60)
            assert st["status"] == FINISHED
            assert st["attempt"] >= 1  # redeployed at least once
            states = [h["state"] for h in st["state_history"]]
            assert "RESTARTING" in states
            # exactly-once despite the rescale restart: every record
            # counted exactly once across all fired windows
            rows = JsonLinesFileSink.read_rows(out)
            per_window = {}
            for r in rows:  # later refires overwrite earlier partials
                per_window[(int(r["key"]), int(r["window_start"]))] = \
                    int(r["count"])
            assert sum(per_window.values()) == 40_000
        finally:
            cluster.shutdown()


class _Contender(LeaderContender):
    def __init__(self):
        self.granted = []
        self.revoked = 0

    def grant_leadership(self, token):
        self.granted.append(token)

    def revoke_leadership(self):
        self.revoked += 1


class TestLeaderElection:
    def test_single_leader_and_takeover(self, tmp_path):
        d = str(tmp_path)
        c1, c2 = _Contender(), _Contender()
        s1 = LeaderElectionService(
            FileLeaderElectionDriver(d, "dispatcher", lease_timeout_s=0.4),
            c1, poll_interval_s=0.05)
        s2 = LeaderElectionService(
            FileLeaderElectionDriver(d, "dispatcher", lease_timeout_s=0.4),
            c2, poll_interval_s=0.05)
        s1.start()
        time.sleep(0.3)
        assert s1.is_leader and c1.granted
        s2.start()
        time.sleep(0.3)
        assert not s2.is_leader  # exactly one leader
        token1 = c1.granted[0]
        # leader dies (stops renewing without releasing)
        s1._stop.set()
        s1._thread.join(timeout=2)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not s2.is_leader:
            time.sleep(0.05)
        assert s2.is_leader and c2.granted
        assert c2.granted[0] != token1  # fresh fencing token
        s2.stop()
        s1.driver.release()

    def test_explicit_release_hands_over_fast(self, tmp_path):
        d = str(tmp_path)
        c1, c2 = _Contender(), _Contender()
        s1 = LeaderElectionService(
            FileLeaderElectionDriver(d, "rm", lease_timeout_s=5.0), c1,
            poll_interval_s=0.05)
        s2 = LeaderElectionService(
            FileLeaderElectionDriver(d, "rm", lease_timeout_s=5.0), c2,
            poll_interval_s=0.05)
        s1.start()
        time.sleep(0.2)
        s2.start()
        s1.stop()  # graceful: releases the lock
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline and not s2.is_leader:
            time.sleep(0.05)
        assert s2.is_leader
        s2.stop()


class TestJobGraphStoreAndBlobs:
    def test_dispatcher_recovers_jobs_after_failover(self, tmp_path):
        ha = str(tmp_path / "ha")
        ck = str(tmp_path / "ck")
        out = str(tmp_path / "o.jsonl")
        cfg = {
            "rest.port": -1,
            "high-availability.type": "filesystem",
            "high-availability.storageDir": ha,
        }
        cluster1 = MiniCluster(Configuration(cfg))
        job_cfg = Configuration({
            "execution.micro-batch.size": 256,
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 2,
        })
        env = StreamExecutionEnvironment(job_cfg)
        build(env, out, total=60_000, source_cls=SlowDataGen)
        client1 = cluster1.submit(env, "ha-job")
        job_id = client1.job_id
        # let it run + checkpoint, then the whole cluster dies
        time.sleep(1.0)
        cluster1.shutdown()
        assert JobGraphStore(ha).job_ids() == [job_id]

        # new cluster over the same HA dir: the job recovers, resumes from
        # its checkpoint and finishes
        cluster2 = MiniCluster(Configuration(cfg))
        try:
            # recovery happens on leadership grant (async): cluster1's
            # graceful shutdown released the lease, cluster2 acquires it
            deadline = time.monotonic() + 10
            master = None
            while time.monotonic() < deadline and master is None:
                master = cluster2.dispatcher.master(job_id)
                time.sleep(0.05)
            assert master is not None, "job not recovered"
            assert master.wait(timeout=60) == FINISHED
            # terminal job leaves the store
            assert JobGraphStore(ha).job_ids() == []
            rows = JsonLinesFileSink.read_rows(out)
            per_window = {}
            for r in rows:
                per_window[(int(r["key"]), int(r["window_start"]))] = \
                    int(r["count"])
            assert sum(per_window.values()) == 60_000
        finally:
            cluster2.shutdown()

    def test_blob_store_roundtrip_and_cache(self, tmp_path):
        store = BlobStore(str(tmp_path / "ha"),
                          cache_dir=str(tmp_path / "cache"))
        key = store.put(b"artifact-bytes")
        assert store.exists(key)
        assert store.get(key) == b"artifact-bytes"
        # cached copy survives deletion at the server
        store.delete(key)
        assert store.get(key) == b"artifact-bytes"
        # content addressing: same bytes -> same key
        assert store.put(b"artifact-bytes") == key
        # corruption is detected
        k2 = BlobStore(str(tmp_path / "ha2")).put(b"x")
        with open(os.path.join(str(tmp_path / "ha2"), "blobs", k2),
                  "wb") as f:
            f.write(b"tampered")
        with pytest.raises(IOError, match="verification"):
            BlobStore(str(tmp_path / "ha2")).get(k2)

    def test_blob_store_corrupted_cache_entry_is_repaired(self, tmp_path):
        """A corrupted LOCAL cache entry must not be served: the
        content-addressed contract holds on the cache-hit path too, falling
        back to a store re-fetch and re-caching the good bytes."""
        cache = str(tmp_path / "cache")
        store = BlobStore(str(tmp_path / "ha"), cache_dir=cache)
        key = store.put(b"artifact-bytes")
        assert store.get(key) == b"artifact-bytes"  # now cached
        with open(os.path.join(cache, key), "wb") as f:
            f.write(b"bit-rot")
        assert store.get(key) == b"artifact-bytes"  # repaired from store
        with open(os.path.join(cache, key), "rb") as f:
            assert f.read() == b"artifact-bytes"  # cache re-populated

    def test_lease_renew_detects_concurrent_steal(self, tmp_path,
                                                  monkeypatch):
        """renew() races a stale-lease os.replace steal: if the steal lands
        between renew's read and its utime, the loser must observe the loss
        (post-touch ownership verification) — otherwise both dispatchers
        believe they hold the lease (split brain)."""
        import json as _json

        d = str(tmp_path / "ha")
        os.makedirs(d)
        a = FileLeaderElectionDriver(d, "dispatcher", lease_timeout_s=60)
        b = FileLeaderElectionDriver(d, "dispatcher", lease_timeout_s=60)
        assert a.try_acquire()
        real_utime = os.utime

        def steal_then_utime(path, *args, **kwargs):
            # interleave: b's steal lands exactly between a's read and touch
            tmp = path + ".steal"
            with open(tmp, "w") as f:
                f.write(_json.dumps({"owner": b.owner_id,
                                     "ts": time.time()}))
            os.replace(tmp, path)
            return real_utime(path, *args, **kwargs)

        monkeypatch.setattr(os, "utime", steal_then_utime)
        assert a.renew() is False  # a must see it lost the lease
        monkeypatch.setattr(os, "utime", real_utime)
        assert b.renew() is True

    def test_revoked_leader_suspends_running_jobs(self, tmp_path):
        """Split-brain guard: when a dispatcher loses its lease, it must
        suspend its running jobs — the new leader resubmits them from the
        JobGraphStore, and two clusters must not run the same job against
        the same checkpoint dir/sinks."""
        import json as _json

        ha = str(tmp_path / "ha")
        cluster = MiniCluster(Configuration({
            "rest.port": -1,
            "high-availability.type": "filesystem",
            "high-availability.storageDir": ha,
            "high-availability.lease-timeout-ms": 400,
        }))
        try:
            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 64}))
            build(env, str(tmp_path / "o.jsonl"), total=2_000_000,
                  source_cls=SlowDataGen)
            client = cluster.submit(env, "long-job")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if client.status()["status"] == "RUNNING":
                    break
                time.sleep(0.02)
            assert client.status()["status"] == "RUNNING"
            # steal the lease out from under the running dispatcher
            lock = os.path.join(ha, "dispatcher.lock")
            with open(lock + ".steal", "w") as f:
                f.write(_json.dumps({"owner": "other-cluster",
                                     "ts": time.time()}))
            os.replace(lock + ".steal", lock)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if client.status()["status"] in ("SUSPENDED", "CANCELED"):
                    break
                time.sleep(0.05)
            assert client.status()["status"] in ("SUSPENDED", "CANCELED")
            # the job stays in the HA store for the new leader
            store = JobGraphStore(ha)
            assert "long-job" in [store.get(j)["job_name"]
                                  for j in store.job_ids()]
        finally:
            cluster.shutdown()

    def test_standby_cluster_does_not_run_jobs(self, tmp_path):
        """Two clusters over one HA storageDir: only the leader recovers
        and runs jobs; the standby waits (reference: standby dispatcher)."""
        ha = str(tmp_path / "ha")
        cfg = {"rest.port": -1,
               "high-availability.type": "filesystem",
               "high-availability.storageDir": ha}
        # seed a job in the store without running it: write directly
        env = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 512}))
        build(env, str(tmp_path / "o.jsonl"), total=2_000)
        graph = env.get_stream_graph()
        JobGraphStore(ha).put("job-x", "seeded", graph,
                              {"execution.micro-batch.size": 512})
        leader = MiniCluster(Configuration(cfg))
        standby = MiniCluster(Configuration(cfg))
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and \
                    leader.dispatcher.master("job-x") is None and \
                    standby.dispatcher.master("job-x") is None:
                time.sleep(0.05)
            ran_on = [c for c in (leader, standby)
                      if c.dispatcher.master("job-x") is not None]
            assert len(ran_on) == 1, "exactly one cluster recovers the job"
        finally:
            standby.shutdown()
            leader.shutdown()
