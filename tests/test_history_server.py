"""History server: terminal jobs archived by the JobMaster and served
after the cluster is gone (reference: HistoryServer +
jobmanager.archive.fs.dir)."""

import json
import urllib.request

import pytest

from flink_tpu import Configuration
from flink_tpu.cluster.history_server import HistoryServer, read_archive
from flink_tpu.cluster.minicluster import MiniCluster
from flink_tpu.connectors.sinks import CollectSink, DiscardingSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _submit(cluster, name, fail=False):
    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 1000,
        "restart-strategy.max-attempts": 1,
    }))
    src = DataGenSource(total_records=5000, num_keys=50,
                        events_per_second_of_eventtime=10_000)
    ds = env.from_source(
        src, WatermarkStrategy.for_bounded_out_of_orderness(0))
    if fail:
        def boom(batch):
            raise RuntimeError("kaboom")

        ds = ds.map(boom, name="boom")
    ds.key_by("key").window(TumblingEventTimeWindows.of(1000)) \
        .sum("value").sink_to(DiscardingSink())
    client = cluster.submit(env, name)
    client.wait(timeout=60)
    return client


class TestHistoryServer:
    def test_terminal_jobs_archived_and_served(self, tmp_path):
        archive = str(tmp_path / "history")
        cluster = MiniCluster(Configuration({
            "cluster.task-executors": 1,
            "jobmanager.archive.dir": archive,
            "rest.port": -1,
        }))
        try:
            ok = _submit(cluster, "good-job")
            bad = _submit(cluster, "bad-job", fail=True)
        finally:
            cluster.shutdown()

        # the cluster is GONE; the archive still answers
        summaries = read_archive(archive)
        by_name = {s["job_name"]: s for s in summaries}
        assert by_name["good-job"]["status"] == "FINISHED"
        assert by_name["bad-job"]["status"] == "FAILED"

        hs = HistoryServer(archive)
        try:
            base = f"http://127.0.0.1:{hs.port}"
            jobs = json.loads(urllib.request.urlopen(
                f"{base}/jobs", timeout=10).read())["jobs"]
            assert {j["job_name"] for j in jobs} == {"good-job", "bad-job"}
            full = json.loads(urllib.request.urlopen(
                f"{base}/jobs/{ok.job_id}", timeout=10).read())
            assert full["status"] == "FINISHED"
            assert full["metrics"]["records_emitted_by_sources"] == 5000
            assert "state_history" in full
            failed = json.loads(urllib.request.urlopen(
                f"{base}/jobs/{bad.job_id}", timeout=10).read())
            assert "kaboom" in failed["error"]
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"{base}/jobs/nope", timeout=10)
        finally:
            hs.close()

    def test_no_archive_dir_no_files(self, tmp_path):
        cluster = MiniCluster(Configuration({
            "cluster.task-executors": 1, "rest.port": -1}))
        try:
            _submit(cluster, "unarchived")
        finally:
            cluster.shutdown()
        assert read_archive(str(tmp_path / "never-created")) == []
