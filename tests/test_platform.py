"""Platform selection: bounded backend probe + CPU fallback.

The execution path must survive an environment whose configured
accelerator backend has a dead transport (plugin hangs in native init) —
``env.execute()`` degrades to CPU after a bounded probe instead of
hanging forever. See tools/tpu_diagnose.py + tpu_results/ for the
committed failure-layer evidence this guards against."""

import os

import pytest

import flink_tpu.platform as platform


@pytest.fixture(autouse=True)
def _reset_memo():
    platform._live_backend = None
    yield
    platform._live_backend = None


def test_cpu_selection_skips_probe(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert platform.ensure_live_backend() == "cpu"


def test_probe_off_trusts_configuration(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.setenv("FLINK_TPU_BACKEND_PROBE", "off")
    assert platform.ensure_live_backend() == "unprobed"


def test_dead_backend_falls_back_to_cpu(monkeypatch, tmp_path):
    """A selection whose init can't succeed within the bound degrades
    to CPU with a warning — and jax keeps working afterwards."""
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")  # no TPU in CI
    monkeypatch.setenv("FLINK_TPU_BACKEND_PROBE_TIMEOUT", "8")
    monkeypatch.setenv("FLINK_TPU_BACKEND_PROBE_CACHE_TTL", "0")
    # keep the machine-wide marker file out of the real tempdir — a
    # 'dead' verdict from this deliberately-short probe must not
    # degrade a real job on the same box
    monkeypatch.setattr(
        platform, "_probe_cache_path",
        lambda sel: str(tmp_path / f"probe_{sel}.json"))
    with pytest.warns(RuntimeWarning, match="falling back to CPU"):
        got = platform.ensure_live_backend()
    assert got == "cpu"
    import jax
    import jax.numpy as jnp

    out = jax.jit(lambda x: x * 2)(jnp.arange(3))
    assert out.tolist() == [0, 2, 4]
    # memoized: second call must not probe again (would re-warn)
    assert platform.ensure_live_backend() == "cpu"


def test_probe_verdict_cached_across_processes(monkeypatch, tmp_path):
    """A fresh process (reset memo) reuses the marker-file verdict
    instead of re-paying the probe timeout."""
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.setenv("FLINK_TPU_BACKEND_PROBE_CACHE_TTL", "300")
    monkeypatch.setattr(
        platform, "_probe_cache_path",
        lambda sel: str(tmp_path / f"probe_{sel}.json"))
    platform._write_probe_cache("tpu", "dead")
    import time

    t0 = time.monotonic()
    got = platform.ensure_live_backend()
    assert got == "cpu"
    assert time.monotonic() - t0 < 2.0  # no subprocess probe ran


def test_execute_calls_probe(monkeypatch):
    """env.execute() consults the probe before touching the device."""
    calls = []
    monkeypatch.setattr(platform, "ensure_live_backend",
                        lambda timeout=45.0: calls.append(1) or "cpu")
    from flink_tpu import Configuration, StreamExecutionEnvironment
    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.connectors.sources import DataGenSource
    from flink_tpu.runtime.watermarks import WatermarkStrategy
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    env = StreamExecutionEnvironment(Configuration())
    sink = CollectSink()
    env.add_source(DataGenSource(total_records=100, num_keys=3,
                                 events_per_second_of_eventtime=100),
                   WatermarkStrategy.for_bounded_out_of_orderness(0)) \
        .key_by("key").window(TumblingEventTimeWindows.of(1000)) \
        .sum("value").sink_to(sink)
    env.execute()
    assert calls, "execute() must invoke ensure_live_backend"
