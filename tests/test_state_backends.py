"""State backend SPI (flink_tpu/state/backends.py).

reference parity: StateBackend SPI with HashMapStateBackend /
EmbeddedRocksDBStateBackend selected by state.backend. Here a backend is
a *placement* — the device the accumulator arrays commit to; kernels
follow the data.

Pins: host-heap results == default results (windows and sessions); the
accumulators really live on the chosen device; unknown backends fail
with the registered list; custom backends register; panes + placement is
rejected; checkpoints round-trip across backends.
"""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.state.backends import register_state_backend, resolve_placement
from flink_tpu.windowing.assigners import (
    EventTimeSessionWindows,
    SlidingEventTimeWindows,
)


def _rows(n=2000, keys=17):
    rng = np.random.default_rng(5)
    return [{"key": int(rng.integers(keys)), "v": float(i % 7), "t": i * 3}
            for i in range(n)]


def _run(backend, assigner, rows, extra=None):
    conf = {"execution.micro-batch.size": 128, "state.backend": backend}
    conf.update(extra or {})
    env = StreamExecutionEnvironment(Configuration(conf))
    result = (
        env.from_collection(rows, timestamp_field="t")
        .key_by("key").window(assigner).sum("v")
        .execute_and_collect()
    )
    return {(r["key"], r["window_start"]): r["sum_v"]
            for r in result.to_rows()}


class TestHostHeap:
    def test_windows_match_default(self):
        rows = _rows()
        a = SlidingEventTimeWindows.of(600, 300)
        assert _run("host-heap", a, rows) == _run("tpu-slot-table", a, rows)

    def test_sessions_match_default(self):
        rows = _rows()
        a = EventTimeSessionWindows.with_gap(50)
        assert _run("host-heap", a, rows) == _run("tpu-slot-table", a, rows)

    def test_accumulators_commit_to_cpu(self):
        import jax

        from flink_tpu.state.slot_table import SlotTable
        from flink_tpu.windowing.aggregates import SumAggregate

        cpu = jax.devices("cpu")[0]
        t = SlotTable(SumAggregate("v"), capacity=1 << 10, device=cpu)
        assert all(list(a.devices()) == [cpu] for a in t.accs)
        t.upsert(np.arange(10, dtype=np.int64),
                 np.zeros(10, dtype=np.int64),
                 (np.ones(10, dtype=np.float32),))
        # placement sticks across donated-buffer kernels
        assert all(list(a.devices()) == [cpu] for a in t.accs)

    def test_checkpoint_crosses_backends(self, tmp_path):
        """A snapshot taken under one placement restores under another —
        snapshots are logical rows, not device buffers."""
        rows = _rows(800)
        a = SlidingEventTimeWindows.of(600, 300)
        conf = {"execution.micro-batch.size": 64,
                "state.backend": "host-heap",
                "execution.checkpointing.every-n-source-batches": 3,
                "state.checkpoints.dir": str(tmp_path / "ckpt")}
        env = StreamExecutionEnvironment(Configuration(conf))
        (env.from_collection(rows, timestamp_field="t")
         .key_by("key").window(a).sum("v")
         .execute_and_collect())
        import os

        chks = [d for d in os.listdir(tmp_path / "ckpt")
                if d.startswith("chk-")]
        assert chks  # checkpoints were written under host-heap placement


class TestRegistry:
    def test_unknown_backend_fails_loudly(self):
        with pytest.raises(ValueError, match="host-heap"):
            resolve_placement("rocksdb")

    def test_custom_backend_registers(self):
        import jax

        register_state_backend("test-pinned",
                               lambda: jax.devices("cpu")[0])
        assert resolve_placement("test-pinned") == jax.devices("cpu")[0]

    def test_panes_with_placement_rejected(self):
        rows = _rows(200)
        with pytest.raises(ValueError, match="panes"):
            _run("host-heap", SlidingEventTimeWindows.of(600, 300), rows,
                 extra={"state.window-layout": "panes"})
