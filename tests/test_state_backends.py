"""State backend SPI (flink_tpu/state/backends.py).

reference parity: StateBackend SPI with HashMapStateBackend /
EmbeddedRocksDBStateBackend selected by state.backend. Here a backend is
a *placement* — the device the accumulator arrays commit to; kernels
follow the data.

Pins: host-heap results == default results (windows and sessions); the
accumulators really live on the chosen device; unknown backends fail
with the registered list; custom backends register; panes + placement is
rejected; checkpoints round-trip across backends.
"""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.state.backends import register_state_backend, resolve_placement
from flink_tpu.windowing.assigners import (
    EventTimeSessionWindows,
    SlidingEventTimeWindows,
)


def _rows(n=2000, keys=17):
    rng = np.random.default_rng(5)
    return [{"key": int(rng.integers(keys)), "v": float(i % 7), "t": i * 3}
            for i in range(n)]


def _run(backend, assigner, rows, extra=None):
    conf = {"execution.micro-batch.size": 128, "state.backend": backend}
    conf.update(extra or {})
    env = StreamExecutionEnvironment(Configuration(conf))
    result = (
        env.from_collection(rows, timestamp_field="t")
        .key_by("key").window(assigner).sum("v")
        .execute_and_collect()
    )
    return {(r["key"], r["window_start"]): r["sum_v"]
            for r in result.to_rows()}


class TestHostHeap:
    def test_windows_match_default(self):
        rows = _rows()
        a = SlidingEventTimeWindows.of(600, 300)
        assert _run("host-heap", a, rows) == _run("tpu-slot-table", a, rows)

    def test_sessions_match_default(self):
        rows = _rows()
        a = EventTimeSessionWindows.with_gap(50)
        assert _run("host-heap", a, rows) == _run("tpu-slot-table", a, rows)

    def test_accumulators_commit_to_cpu(self):
        import jax

        from flink_tpu.state.slot_table import SlotTable
        from flink_tpu.windowing.aggregates import SumAggregate

        cpu = jax.devices("cpu")[0]
        t = SlotTable(SumAggregate("v"), capacity=1 << 10, device=cpu)
        assert all(list(a.devices()) == [cpu] for a in t.accs)
        t.upsert(np.arange(10, dtype=np.int64),
                 np.zeros(10, dtype=np.int64),
                 (np.ones(10, dtype=np.float32),))
        # placement sticks across donated-buffer kernels
        assert all(list(a.devices()) == [cpu] for a in t.accs)

    def test_snapshot_crosses_backends(self):
        """A snapshot taken under one placement restores under another —
        snapshots are logical rows, not device buffers. Ingest half the
        stream on host-heap, snapshot, restore onto the default
        placement, ingest the rest: fires must equal a single-placement
        run."""
        import jax

        from flink_tpu.windowing.aggregates import SumAggregate
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows
        from flink_tpu.windowing.windower import SliceSharedWindower
        from flink_tpu.core.records import RecordBatch

        rng = np.random.default_rng(2)
        n = 3000
        keys = rng.integers(0, 20, n).astype(np.int64)
        vals = rng.random(n).astype(np.float32)
        ts = np.arange(n, dtype=np.int64) * 2

        def batch(sl):
            return RecordBatch(
                {"__key_id__": keys[sl], "v": vals[sl], "__ts__": ts[sl]})

        assigner = TumblingEventTimeWindows.of(1000)
        cpu = jax.devices("cpu")[0]

        w1 = SliceSharedWindower(assigner, SumAggregate("v"),
                                 capacity=1 << 12,
                                 spill={"device": cpu})
        w1.process_batch(batch(slice(0, n // 2)))
        snap = w1.snapshot()
        w2 = SliceSharedWindower(assigner, SumAggregate("v"),
                                 capacity=1 << 12)  # default placement
        w2.restore(snap)
        w2.process_batch(batch(slice(n // 2, n)))
        fired = w2.on_watermark(int(ts[-1]) + 1000)

        ref = SliceSharedWindower(assigner, SumAggregate("v"),
                                  capacity=1 << 12)
        ref.process_batch(batch(slice(0, n)))
        expect = ref.on_watermark(int(ts[-1]) + 1000)

        def flat(batches):
            out = {}
            for b in batches:
                for r in b.to_rows():
                    out[(r["__key_id__"], r["window_start"])] = round(
                        float(r["sum_v"]), 3)
            return out

        assert flat(fired) == flat(expect) and len(flat(expect)) > 20

    def test_checkpoints_written_under_host_heap(self, tmp_path):
        rows = _rows(800)
        a = SlidingEventTimeWindows.of(600, 300)
        conf = {"execution.micro-batch.size": 64,
                "state.backend": "host-heap",
                "execution.checkpointing.every-n-source-batches": 3,
                "state.checkpoints.dir": str(tmp_path / "ckpt")}
        env = StreamExecutionEnvironment(Configuration(conf))
        (env.from_collection(rows, timestamp_field="t")
         .key_by("key").window(a).sum("v")
         .execute_and_collect())
        import os

        chks = [d for d in os.listdir(tmp_path / "ckpt")
                if d.startswith("chk-")]
        assert chks


class TestRegistry:
    def test_unknown_backend_fails_loudly(self):
        with pytest.raises(ValueError, match="host-heap"):
            resolve_placement("rocksdb")

    def test_custom_backend_registers(self):
        import jax

        register_state_backend("test-pinned",
                               lambda: jax.devices("cpu")[0])
        assert resolve_placement("test-pinned") == jax.devices("cpu")[0]

    def test_panes_with_placement_rejected(self):
        rows = _rows(200)
        with pytest.raises(ValueError, match="panes"):
            _run("host-heap", SlidingEventTimeWindows.of(600, 300), rows,
                 extra={"state.window-layout": "panes"})

    def test_placement_on_mesh_path_fails_loudly(self):
        """A placement backend at operator parallelism > 1 must raise,
        never silently degrade (the mesh places state itself)."""
        from flink_tpu.runtime.operators import (
            OperatorContext,
            WindowAggOperator,
        )
        from flink_tpu.windowing.aggregates import SumAggregate

        op = WindowAggOperator(
            SlidingEventTimeWindows.of(600, 300), SumAggregate("v"),
            "key", state_backend="host-heap")
        with pytest.raises(ValueError, match="parallelism > 1"):
            op.open(OperatorContext(parallelism=8, max_parallelism=128))

    def test_placement_honored_by_stage_parallel_subtasks(self):
        """Stage-parallel subtasks open single-device engines — the
        placement applies there (the supported parallel form)."""
        rows = _rows(600)
        base = _run("tpu-slot-table",
                    SlidingEventTimeWindows.of(600, 300), rows)
        got = _run("host-heap", SlidingEventTimeWindows.of(600, 300),
                   rows, extra={"execution.stage-parallelism": 2})
        assert got.keys() == base.keys()
        for k in base:
            assert got[k] == pytest.approx(base[k], rel=1e-5)
