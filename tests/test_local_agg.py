"""Two-phase (local/global) aggregation — flink_tpu/runtime/local_agg.py.

reference parity: MiniBatchLocalGroupAggFunction +
MiniBatchGlobalGroupAggFunction (agg-phase-strategy TWO_PHASE); SURVEY §2.9
local/global row; hard-part (e) key skew.

The combiner runs on stage-parallel source subtasks, collapsing each batch
to one row per (key, slice) with per-leaf partials; the keyed stage folds
those with scatter_valued. Pinned here:

- combiner output matches a brute-force per-group reduce (sum/max/count,
  const leaves materialized);
- stage-parallel results with local agg ON == OFF == single-slot oracle;
- shuffle volume actually shrinks on a skewed stream;
- partial batches fold correctly through the single-device windower
  (both layouts) — the global half in isolation.
"""

import collections

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.local_agg import (
    PARTIAL_LEAF_PREFIX,
    LocalWindowCombiner,
    is_partial_batch,
)
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.aggregates import (
    CountAggregate,
    MaxAggregate,
    MultiAggregate,
    SumAggregate,
)
from flink_tpu.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)


def _batch(n, keys, seed=0):
    rng = np.random.default_rng(seed)
    return RecordBatch.from_pydict(
        {"key": rng.integers(0, keys, n),
         "v": rng.random(n).astype(np.float32)},
        timestamps=rng.integers(0, 10_000, n))


class TestCombiner:
    def test_matches_bruteforce(self):
        agg = MultiAggregate([SumAggregate("v"), CountAggregate(),
                              MaxAggregate("v")])
        assigner = TumblingEventTimeWindows.of(1000)
        c = LocalWindowCombiner(assigner, agg, "key")
        b = _batch(5000, 40)
        out = c.combine(b)
        assert is_partial_batch(out)
        # brute force per (key, slice)
        exp = {}
        se = assigner.assign_slice_ends(b.timestamps)
        for k, v, s, ts in zip(b["key"], b["v"], se, b.timestamps):
            e = exp.setdefault((int(k), int(s)),
                               [0.0, 0, -np.inf, -1])
            e[0] += float(v)
            e[1] += 1
            e[2] = max(e[2], float(v))
            e[3] = max(e[3], int(ts))
        assert len(out) == len(exp)
        se_out = assigner.assign_slice_ends(out.timestamps)
        for i in range(len(out)):
            k = (int(out["key"][i]), int(se_out[i]))
            e = exp[k]
            assert out[PARTIAL_LEAF_PREFIX + "0"][i] == pytest.approx(
                e[0], rel=1e-5)
            assert int(out[PARTIAL_LEAF_PREFIX + "1"][i]) == e[1]
            assert out[PARTIAL_LEAF_PREFIX + "2"][i] == pytest.approx(e[2])
            assert int(out.timestamps[i]) == e[3]

    def test_merging_assigner_rejected(self):
        from flink_tpu.windowing.assigners import EventTimeSessionWindows

        with pytest.raises(ValueError, match="aligned"):
            LocalWindowCombiner(EventTimeSessionWindows.with_gap(100),
                                CountAggregate(), "key")


class TestGlobalFold:
    @pytest.mark.parametrize("layout", ["slots", "panes"])
    def test_partial_batches_through_windower(self, layout):
        """Feeding pre-combined batches into the window operator gives the
        same windows as feeding the raw batches."""

        def run(pre_combine):
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 500,
                "state.window-layout": layout,
            }))
            sink = CollectSink()
            src = DataGenSource(total_records=20_000, num_keys=100,
                                events_per_second_of_eventtime=10_000,
                                seed=3)
            ds = env.from_source(
                src, WatermarkStrategy.for_bounded_out_of_orderness(0))
            if pre_combine:
                comb = LocalWindowCombiner(
                    SlidingEventTimeWindows.of(2000, 1000),
                    MultiAggregate([SumAggregate("value"),
                                    CountAggregate()]), "key")
                ds = ds.map(comb.combine, name="local_combine")
            (ds.key_by("key")
             .window(SlidingEventTimeWindows.of(2000, 1000))
             .aggregate(MultiAggregate([SumAggregate("value"),
                                        CountAggregate()]))
             .sink_to(sink))
            env.execute()
            return {(r["key"], r["window_start"]):
                    (r["sum_value"], r["count"])
                    for r in sink.result().to_rows()}

        on, off = run(True), run(False)
        assert set(on) == set(off) and len(on) > 50
        for k in off:
            # f32 summation order differs between pre-combined and raw
            # folds — equal up to float tolerance, counts exact
            assert on[k][0] == pytest.approx(off[k][0], rel=1e-4)
            assert on[k][1] == off[k][1]


class TestStageParallelTwoPhase:
    def _run(self, local_agg, skew_keys=10):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1000,
            "execution.stage-parallelism": 4,
            "execution.source-parallelism": 1,
            "execution.local-agg": local_agg,
            "state.slot-table.capacity": 8192,
        }))
        sink = CollectSink()
        src = DataGenSource(total_records=30_000, num_keys=skew_keys,
                            events_per_second_of_eventtime=10_000, seed=7)
        (env.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0))
         .key_by("key").window(TumblingEventTimeWindows.of(1000))
         .sum("value").sink_to(sink))
        result = env.execute()
        got = {(r["key"], r["window_start"]): r["sum_value"]
               for r in sink.result().to_rows()}
        return got, result

    def test_results_equal_and_volume_shrinks(self):
        on, res_on = self._run(True)
        off, res_off = self._run(False)
        assert set(on) == set(off) and len(on) > 5
        for k in off:
            assert on[k] == pytest.approx(off[k], rel=1e-4)
        # source records are counted pre-combine; both runs saw the same
        assert res_on.metrics["records"] == res_off.metrics["records"]
        # the skewed stream (10 hot keys) must collapse hard across the
        # exchange: at most keys x slices rows per batch leave a subtask
        assert res_on.metrics["records_shuffled"] < \
            res_off.metrics["records_shuffled"] / 5, (
                res_on.metrics["records_shuffled"],
                res_off.metrics["records_shuffled"])
