"""Profile the 10M-key sessions row at the THRASHING shape (live
sessions > device slot budget) — the BASELINE row-5 workload the round-4
bench moved out of measurement. Used to attack the spill-tier bound.

Usage: python tools/profile_sessions.py [n_records] [evps] [--cprofile]
"""

import cProfile
import io
import pstats
import sys
import time

sys.path.insert(0, ".")
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def run(n, evps):
    from flink_tpu import Configuration, StreamExecutionEnvironment
    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.connectors.sources import DataGenSource
    from flink_tpu.runtime.watermarks import WatermarkStrategy
    from flink_tpu.windowing.assigners import EventTimeSessionWindows

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 1 << 16,
        "state.slot-table.capacity": 1 << 19,
        "state.slot-table.max-device-slots": 1 << 19,
    }))
    sink = CollectSink()
    # evps of event time x 2 s gap = 2*evps live sessions; at 400k ev/s
    # that is ~800k live vs the 512k budget -> sustained spill pressure
    src = DataGenSource(total_records=n, num_keys=10_000_000,
                        events_per_second_of_eventtime=evps, seed=3)
    (env.from_source(
        src, WatermarkStrategy.for_bounded_out_of_orderness(0))
       .key_by("key")
       .window(EventTimeSessionWindows.with_gap(2_000))
       .sum("value").sink_to(sink))
    t0 = time.perf_counter()
    env.execute("sessions-thrash")
    dt = time.perf_counter() - t0
    print(f"{n} records in {dt:.1f}s = {n / dt:,.0f} ev/s "
          f"(real-time bar: {evps:,}/s), results={len(sink.result())}")
    return n / dt


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    n = int(args[0]) if args else 2_000_000
    evps = int(args[1]) if len(args) > 1 else 400_000
    if "--cprofile" in sys.argv:
        pr = cProfile.Profile()
        pr.enable()
        run(n, evps)
        pr.disable()
        s = io.StringIO()
        pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(40)
        print(s.getvalue())
    else:
        run(n, evps)


if __name__ == "__main__":
    main()
