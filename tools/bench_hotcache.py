"""Hot-row cache per-hit microbench: GIL-held dict path vs GIL-free
native probe table (the r19 native serving fast path's direct cost
evidence, and the source of the serving smoke's per-hit-cost gate).

Three paths, measured over identical entries and identical key batches,
INTERLEAVED round-robin with medians (this 1-core box's scheduler noise
swings a sequential A-then-B comparison by 2x):

- ``python_hit_ns`` — ``HotRowCache.get_many``: the pre-r19 hit path,
  one locked OrderedDict probe per key, everything under the GIL.
- ``native_hit_ns`` — ``NativeHotRowCache.get_many_packed``: ONE C call
  for the whole batch (GIL released for the probe+memcpy), results stay
  in the packed buffers (the serving fast path — dicts only built for
  keys a consumer actually reads).
- ``native_dict_hit_ns`` — the native probe PLUS eager per-key dict
  materialization (what a caller pays when it does consume every key —
  the honest disclosure: building Python dicts costs more than the
  probe itself, which is exactly why the fast path stays packed).

Also measures ``concurrent_scale``: aggregate probe throughput with 2
threads vs 1, native vs python — the GIL-release evidence (on a 1-core
box the ceiling is the clock, so the signal is the python path
DEGRADING under contention while the native path holds).

    python tools/bench_hotcache.py
    BENCH_HOTCACHE_MIN_RATIO=2.0 python tools/bench_hotcache.py  # gate
"""

import gc
import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _fill(cache, keys):
    vals = [{60_000 * (k % 4 + 1): {"sum_value": float(k)}}
            for k in range(keys)]
    cache.put_many("j", "op", list(range(keys)), 1, vals)


def measure_hit_cost(keys: int = 4096, batch: int = 256,
                     batches_per_round: int = 50, rounds: int = 15):
    """{python_hit_ns, native_hit_ns, native_dict_hit_ns, ratio} — or
    None when the native library is unavailable. Median of interleaved
    rounds; all paths 100% hits over the same batches."""
    from flink_tpu.native import hotcache_available
    from flink_tpu.tenancy.hot_cache import HotRowCache

    if not hotcache_available():
        return None
    from flink_tpu.tenancy.hot_cache_native import NativeHotRowCache

    nc = NativeHotRowCache(max_entries=1 << 18)
    pc = HotRowCache(max_entries=1 << 18)
    _fill(nc, keys)
    _fill(pc, keys)
    rng = np.random.default_rng(0)
    probes = [rng.integers(0, keys, batch) for _ in range(
        batches_per_round)]
    probes_l = [b.tolist() for b in probes]
    n_lookups = batches_per_round * batch

    def py_path():
        for b in probes_l:
            pc.get_many("j", "op", b, 1, [None] * batch, [],
                        exact=False)

    def native_packed():
        for b in probes:
            nc.get_many_packed("j", "op", b, 1, [None] * batch, [],
                               exact=False)

    def native_dict():
        for b in probes:
            nc.get_many("j", "op", b, 1, [None] * batch, [],
                        exact=False)

    res = {"python": [], "native": [], "native_dict": []}
    gc.disable()
    try:
        for _ in range(rounds):
            for name, fn in (("native", native_packed),
                             ("python", py_path),
                             ("native_dict", native_dict)):
                t0 = time.perf_counter()
                fn()
                res[name].append(
                    (time.perf_counter() - t0) / n_lookups * 1e9)
    finally:
        gc.enable()
    out = {
        "python_hit_ns": statistics.median(res["python"]),
        "native_hit_ns": statistics.median(res["native"]),
        "native_dict_hit_ns": statistics.median(res["native_dict"]),
    }
    out["ratio"] = out["python_hit_ns"] / out["native_hit_ns"] \
        if out["native_hit_ns"] else 0.0
    nc.close()
    return out


def measure_concurrent(keys: int = 4096, batch: int = 256,
                       seconds: float = 1.0):
    """Aggregate probes/s, 1 thread vs 2 threads, native vs python —
    the GIL-held-vs-released evidence. Returns None without native."""
    from flink_tpu.native import hotcache_available
    from flink_tpu.tenancy.hot_cache import HotRowCache

    if not hotcache_available():
        return None
    from flink_tpu.tenancy.hot_cache_native import NativeHotRowCache

    nc = NativeHotRowCache(max_entries=1 << 18)
    pc = HotRowCache(max_entries=1 << 18)
    _fill(nc, keys)
    _fill(pc, keys)
    rng = np.random.default_rng(1)
    b_arr = rng.integers(0, keys, batch)
    b_list = b_arr.tolist()

    def run(fn, n_threads):
        stop = threading.Event()
        counts = [0] * n_threads

        def worker(i):
            while not stop.is_set():
                fn()
                counts[i] += batch

        ts = [threading.Thread(target=worker, args=(i,), daemon=True)
              for i in range(n_threads)]
        for t in ts:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in ts:
            t.join(timeout=5)
        return sum(counts) / seconds

    def native_fn():
        nc.get_many_packed("j", "op", b_arr, 1, [None] * batch, [],
                           exact=False)

    def py_fn():
        pc.get_many("j", "op", b_list, 1, [None] * batch, [],
                    exact=False)

    out = {
        "native_1t_per_s": run(native_fn, 1),
        "native_2t_per_s": run(native_fn, 2),
        "python_1t_per_s": run(py_fn, 1),
        "python_2t_per_s": run(py_fn, 2),
    }
    nc.close()
    return out


def main():
    min_ratio = float(os.environ.get("BENCH_HOTCACHE_MIN_RATIO", 0))
    cost = measure_hit_cost()
    if cost is None:
        print("hotcache microbench: native library unavailable "
              "(nothing to compare)")
        return 0 if min_ratio == 0 else 1
    conc = measure_concurrent()
    print(json.dumps({
        "metric": "hotcache_hit_ns",
        "value": round(cost["native_hit_ns"], 1),
        "unit": "ns/lookup",
        "shape": (
            f"batched 256-key probes over 4096 hot entries — native "
            f"packed (GIL-released) {cost['native_hit_ns']:.0f} ns vs "
            f"Python dict (GIL-held) {cost['python_hit_ns']:.0f} ns "
            f"({cost['ratio']:.1f}x); native + eager dict build "
            f"{cost['native_dict_hit_ns']:.0f} ns"),
    }), flush=True)
    if conc:
        print(json.dumps({
            "metric": "hotcache_concurrent_probes_per_s",
            "value": round(conc["native_2t_per_s"], 0),
            "unit": "probes/s",
            "shape": (
                f"2 threads native {conc['native_2t_per_s']:,.0f}/s "
                f"(1t {conc['native_1t_per_s']:,.0f}) vs python "
                f"{conc['python_2t_per_s']:,.0f}/s "
                f"(1t {conc['python_1t_per_s']:,.0f})"),
        }), flush=True)
    if min_ratio and cost["ratio"] < min_ratio:
        print(f"FAIL: native hit path only {cost['ratio']:.2f}x "
              f"cheaper than the Python dict path "
              f"(floor {min_ratio:.1f}x)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
