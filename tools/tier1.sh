#!/usr/bin/env bash
# Tier-1 gate — the ONE command builders and CI both run, pinned to the
# exact ROADMAP.md verify invocation (JAX_PLATFORMS=cpu, timeout, marker
# filter) plus a CPU bench smoke, so the gate never drifts between
# environments.
#
#   bash tools/tier1.sh            # tests + bench smoke
#   SKIP_BENCH_SMOKE=1 bash tools/tier1.sh   # tests only

set -u
cd "$(dirname "$0")/.."

# flint: TPU-tracing static analysis over the whole package (host syncs
# on the hot path, tracer-unsafe control flow, unstable jit identities,
# fault-point/metric registry drift). Pure AST — runs in ~2 s, gates
# first so a hot-path regression fails before the long test run.
# flint_report.json is the machine-readable artifact.
python -m tools.flint flink_tpu/ --fail-on-violation \
  --json flint_report.json || exit 1

set -o pipefail
log="${T1_LOG:-/tmp/_t1.$$.log}"   # unique per run: concurrent gates must not clobber
rm -f "$log"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
  -p no:xdist -p no:randomly 2>&1 | tee "$log"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$log" \
  | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
  exit "$rc"
fi

if [ "${SKIP_BENCH_SMOKE:-0}" != "1" ]; then
  # CPU bench smoke: a reduced Q5 run must still emit its JSON line
  # (catches import/config regressions the unit tests cannot)
  BENCH_SKIP_PROBE=1 BENCH_RECORDS=$((1 << 20)) BENCH_REPS=1 \
    JAX_PLATFORMS=cpu timeout -k 10 600 python bench.py || exit 1

  # Mesh-sessions smoke with two gates pinned:
  # (1) page-rewrite amplification: FAILS if (rows_split_on_reload +
  #     rows_compacted) / rows_reloaded exceeds the budget. The lazy
  #     tombstone design's only rewrites are threshold compactions
  #     (~0.2x measured); the old split-on-reload path sat at ~16x.
  # (2) host-prep fraction (device-shuffle mode): FAILS if genuine
  #     host work (sessionization + slot resolution + flat staging,
  #     with fence blocks and inline device interactions attributed to
  #     device time) exceeds the budget share of wall clock — the
  #     regression class where exchange work silently moves back onto
  #     the host. Budget 0.45 vs ~0.40 measured on the 1-core CI host:
  #     the REMAINING host prep is session metadata + host index work
  #     (the shuffle staging itself is <1% of wall clock); the
  #     aspirational 0.25 needs a native metadata plane (NOTES_r11).
  # 2M records so the live session set genuinely exceeds the 512k
  # device budget — below ~1M the tier never spills and the
  # amplification gate would be vacuous.
  BENCH_SKIP_PROBE=1 BENCH_MESH_SESSION_RECORDS=$((1 << 21)) \
    BENCH_MESH_REPS=1 BENCH_MESH_AMP_BUDGET=0.5 \
    BENCH_HOST_PREP_BUDGET=0.45 \
    JAX_PLATFORMS=cpu timeout -k 10 600 \
    python tools/bench_mesh_sessions.py || exit 1

  # Chaos smoke: seeded crash-restore-verify (3 injected engine crashes
  # — incl. the device data plane dying after the fused exchange
  # dispatch — + 1 torn checkpoint write over ~12k events) — FAILS on
  # any output divergence from the fault-free oracle, on a missed
  # injection, or if the torn checkpoint is restored instead of
  # skipped. ~5 s on CPU.
  JAX_PLATFORMS=cpu timeout -k 10 120 \
    python tools/chaos_smoke.py || exit 1

  # Autoscale smoke: deterministic load ramp through the DS2 policy —
  # the mesh session engine must LIVE-rescale 2 -> 4 -> 2 (key-group
  # migration, no stop-redeploy) and finish bit-identical to the
  # single-device oracle. FAILS if the policy never scales, a rescale
  # takes a non-live path, or any window diverges. ~3 s on CPU.
  JAX_PLATFORMS=cpu timeout -k 10 120 \
    python tools/autoscale_smoke.py || exit 1

  # Recompile sentinel: after one warmup rep, 2 measured reps on FRESH
  # engines (both mesh engines, spill armed, disarmed chaos) must show
  # ZERO XLA backend compiles and bounded device->host transfers —
  # jax.monitoring counts real compilations, so a jit identity or
  # padded shape varying per step fails here even though every
  # correctness test still passes. Includes the multi-tenant phase: a
  # SECOND job's fresh engines interleaved on the warm cluster (plus
  # batched serving lookups) must also compile nothing. ~20 s on CPU.
  JAX_PLATFORMS=cpu timeout -k 10 300 \
    python tools/recompile_smoke.py || exit 1

  # Serving smoke: 2 concurrent jobs on one mesh + client threads
  # hammering coalesced queryable-state lookups. FAILS on any
  # steady-state XLA compile after job-1 warms the shared program
  # cache, on a per-job program-cache miss, on lookup p99 over budget,
  # or on a quota violation. ~60 s on CPU.
  SERVING_SMOKE_RECORDS=$((1 << 17)) \
    JAX_PLATFORMS=cpu timeout -k 10 300 \
    python tools/serving_smoke.py || exit 1
fi
