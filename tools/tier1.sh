#!/usr/bin/env bash
# Tier-1 gate — the ONE command builders and CI both run, pinned to the
# exact ROADMAP.md verify invocation (JAX_PLATFORMS=cpu, timeout, marker
# filter) plus a CPU bench smoke, so the gate never drifts between
# environments.
#
#   bash tools/tier1.sh            # tests + bench smoke
#   SKIP_BENCH_SMOKE=1 bash tools/tier1.sh   # tests only

set -u
cd "$(dirname "$0")/.."

# flint: TPU-tracing static analysis over the whole package (host syncs
# on the hot path, tracer-unsafe control flow, unstable jit identities,
# fault-point/metric registry drift). Pure AST — runs in ~2 s, gates
# first so a hot-path regression fails before the long test run.
# flint_report.json is the machine-readable artifact.
python -m tools.flint flink_tpu/ --fail-on-violation \
  --json flint_report.json || exit 1

# Native libraries build UP FRONT and LOUDLY (slotmap, sessions, codec,
# datagen): a missing compiler used to surface as a silent pure-Python
# fallback mid-suite — now it is one explicit line, and when the build
# succeeds the bench smoke REQUIRES the native session plane (no
# vacuous green on the host-prep gate).
native_status="$(python -c 'from flink_tpu.native import build_report; print(build_report())')"
echo "$native_status"
# the no-vacuous-green gate is keyed on the SESSIONS library
# specifically — an unrelated codec/datagen build failure must not
# silently disable the metadata-plane requirement
if python -c 'import sys; from flink_tpu.native import sessions_available; sys.exit(0 if sessions_available() else 1)'; then
  export BENCH_REQUIRE_NATIVE=1
fi
# same discipline for the serving fast path: when the HOTCACHE library
# built, the serving smoke FAILS if the plane silently fell back to
# the Python cache (its throughput/per-hit gates would go vacuous)
if python -c 'import sys; from flink_tpu.native import hotcache_available; sys.exit(0 if hotcache_available() else 1)'; then
  export SERVING_REQUIRE_NATIVE_HOTCACHE=1
fi

set -o pipefail
log="${T1_LOG:-/tmp/_t1.$$.log}"   # unique per run: concurrent gates must not clobber
rm -f "$log"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
  -p no:xdist -p no:randomly 2>&1 | tee "$log"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$log" \
  | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
  exit "$rc"
fi

if [ "${SKIP_BENCH_SMOKE:-0}" != "1" ]; then
  # CPU bench smoke: a reduced Q5 run must still emit its JSON line
  # (catches import/config regressions the unit tests cannot)
  BENCH_SKIP_PROBE=1 BENCH_RECORDS=$((1 << 20)) BENCH_REPS=1 \
    JAX_PLATFORMS=cpu timeout -k 10 600 python bench.py || exit 1

  # Mesh-sessions smoke with two gates pinned:
  # (1) page-rewrite amplification: FAILS if (rows_split_on_reload +
  #     rows_compacted) / rows_reloaded exceeds the budget. The lazy
  #     tombstone design's only rewrites are threshold compactions
  #     (~0.2x measured); the old split-on-reload path sat at ~16x.
  # (2) host-prep fraction (device-shuffle mode): FAILS if genuine
  #     host work (sessionization + slot resolution + flat staging,
  #     with fence blocks and inline device interactions attributed to
  #     device time) exceeds the budget share of wall clock — the
  #     regression class where exchange or metadata work silently
  #     moves back onto the host. Budget 0.35 (tightened from 0.45
  #     when the NATIVE metadata plane landed — sessionize/absorb/
  #     slot-fold/pop run as one C sweep per batch, NOTES_r12) vs
  #     ~0.34 measured on the 1-core CI host. BENCH_REQUIRE_NATIVE
  #     (exported above when the up-front build succeeded) makes the
  #     smoke FAIL rather than silently measure the pure-Python plane.
  # (3) fire p99 (the latency tier, ROADMAP item 1): FAILS if the
  #     MEDIAN of the reps' fire p99 (watermark advance -> results on
  #     host, steady state — the end-of-input drain is excluded and
  #     reported as final_drain_ms) exceeds the budget at the
  #     mesh-sessions smoke shape, or if the smoke recorded < 10 fires
  #     (vacuity guard — a shape that fires too rarely measures
  #     nothing). Budget 140 ms vs ~90-120 measured with the 25 ms
  #     fire deadline on the 1-core CI box; the legacy whole-batch
  #     path (BENCH_MESH_FIRE_DEADLINE_MS=0) measures ~164 ms median
  #     here, so a regression to full-harvest fires trips the gate.
  # 2M records so the live session set genuinely exceeds the 512k
  # device budget — below ~1M the tier never spills and the
  # amplification gate would be vacuous. 3 reps: all gates read the
  # MEDIAN rep (the bench's own methodology) — a single-rep gate at a
  # tight budget tripped on scheduler noise, not regressions.
  BENCH_SKIP_PROBE=1 BENCH_MESH_SESSION_RECORDS=$((1 << 21)) \
    BENCH_MESH_REPS=3 BENCH_MESH_AMP_BUDGET=0.5 \
    BENCH_HOST_PREP_BUDGET=0.35 \
    BENCH_FIRE_P99_BUDGET=140 BENCH_MESH_FIRE_DEADLINE_MS=25 \
    JAX_PLATFORMS=cpu timeout -k 10 600 \
    python tools/bench_mesh_sessions.py || exit 1

  # Trace smoke: the flight recorder's gate at the SAME bench shape —
  # (1) a captured Chrome/Perfetto trace must be schema-valid (every
  #     event a registered KNOWN_SPAN_KINDS kind, batch + watermark +
  #     per-shard attribution present),
  # (2) the measured pass must record 0 steady-state XLA compiles
  #     (the compile-correlation agrees with the recompile sentinel),
  # (3) recorder overhead must stay under 3% of the pass's wall
  #     clock, gated on a DIRECT measurement (live-microbenched
  #     per-record cost x the pass's actual record count / wall;
  #     ~0.05% measured), with the A/B on/off throughput ratio
  #     sanity-bounded at 15% — scheduler noise on this 1-core box is
  #     ~±10%, so a tight A/B gate would flake on noise, not
  #     regressions. ~25 s on CPU.
  TRACE_SMOKE_RECORDS=$((1 << 20)) \
    JAX_PLATFORMS=cpu timeout -k 10 300 \
    python tools/trace_smoke.py || exit 1

  # Chaos smoke: seeded crash-restore-verify (3 injected engine crashes
  # — incl. the device data plane dying after the fused exchange
  # dispatch — + 1 torn checkpoint write over ~12k events) — FAILS on
  # any output divergence from the fault-free oracle, on a missed
  # injection, or if the torn checkpoint is restored instead of
  # skipped. ~5 s on CPU.
  JAX_PLATFORMS=cpu timeout -k 10 120 \
    python tools/chaos_smoke.py || exit 1

  # Autoscale smoke: deterministic load ramp through the DS2 policy —
  # the mesh session engine must LIVE-rescale 2 -> 4 -> 2 (key-group
  # migration, no stop-redeploy) and finish bit-identical to the
  # single-device oracle. FAILS if the policy never scales, a rescale
  # takes a non-live path, or any window diverges. ~3 s on CPU.
  JAX_PLATFORMS=cpu timeout -k 10 120 \
    python tools/autoscale_smoke.py || exit 1

  # Skew smoke: a skewed stream (one key ~40% of records) through the
  # LIVE SkewResponder next to a uniform control — FAILS if no key
  # group moved live, the dominant key never split (zero salted
  # records/fires: vacuous), the moves did not improve measured
  # imbalance, the output diverges from the single-device oracle by
  # one window (bit-identity — integer-valued floats keep the salted
  # fold exact), or skewed throughput drops below BENCH_SKEW_RECOVERY
  # (0.7) of the uniform control — the responder-thrash regression
  # class. ~90 s on CPU.
  BENCH_SKEW_RECOVERY=0.7 JAX_PLATFORMS=cpu timeout -k 10 300 \
    python tools/skew_smoke.py || exit 1

  # Join smoke: the device-native interval + temporal join engines vs
  # the host-numpy oracle — FAILS on any bit divergence (values OR
  # order), on a steady-state XLA compile after warmup, or on a
  # vacuous run where the spill tier never engages (rows must evict
  # AND cold band candidates must serve from pages). ~2 s on CPU.
  JAX_PLATFORMS=cpu timeout -k 10 120 \
    python tools/join_smoke.py || exit 1

  # CEP smoke: the device-vectorized mesh NFA engine vs the host
  # CepOperator oracle — FAILS on any bit divergence (values OR
  # emission order) across both after-match skip strategies and a
  # forced-paged-eviction leg, on a steady-state XLA compile from a
  # FRESH engine on the warm program cache, on a vacuous run (zero
  # matches, rows_evicted=0 or rows_reloaded=0), on a replica-plane
  # matched-pattern lookup diverging from the live store, or on the
  # frontend leg: the same lookups through the multi-process shm
  # serving tier (CepMatchServingAdapter) must decode bit-identical
  # with > 0 shm hits (skipped loudly without the native hotcache).
  # ~10 s on CPU.
  JAX_PLATFORMS=cpu timeout -k 10 120 \
    python tools/cep_smoke.py || exit 1

  # Pallas A/B gate: the stateplane's first Pallas kernel (the
  # exchange-rank counting sort) vs the XLA one-hot-cumsum it
  # replaces — FAILS on any bit divergence at the kernel level
  # (random shapes incl. out-of-range/negative lanes), the cached-
  # program level (xla and pallas keys must also be DISTINCT cache
  # entries), or the engine level (device-mode session fires must be
  # bit-identical IN ORDER across backends). Interpret mode on CPU;
  # SKIPS LOUDLY (exit 0, unmistakable marker line) when the pallas
  # kernel is unavailable on this host. ~20 s on CPU.
  JAX_PLATFORMS=cpu timeout -k 10 300 \
    python tools/pallas_ab_gate.py || exit 1

  # Multi-process smoke: 2 REAL CPU processes (jax.distributed + gloo
  # collectives), each owning half the key-group space, exchanging
  # records over the DCN axis of the process-spanning mesh ON DEVICE
  # (the pod data plane, ROADMAP item 2). FAILS on output divergence
  # from the 1-process run (bit-identity), on any steady-state compile
  # in the measured rep, on a vacuous run (0 rows crossed a process
  # boundary), or on the chaos leg: kill 1 of 2 processes mid-stream —
  # the survivor must restore ONLY the dead host's key-group ranges
  # from its checkpoint units, replay within the per-host bound, and
  # finish bit-identical. Also emits the mesh_sessions_2proc scaling
  # numbers (gateable via MP_SMOKE_MIN_SCALING on multi-core boxes —
  # this 1-core box time-shares the clock, NOTES_r18.md). ~2 min.
  MP_SMOKE_RECORDS=$((1 << 16)) \
    timeout -k 10 600 python tools/multiproc_smoke.py || exit 1

  # Recompile sentinel: after one warmup rep, 2 measured reps on FRESH
  # engines (both mesh engines, spill armed, disarmed chaos) must show
  # ZERO XLA backend compiles and bounded device->host transfers —
  # jax.monitoring counts real compilations, so a jit identity or
  # padded shape varying per step fails here even though every
  # correctness test still passes. Includes the multi-tenant phase: a
  # SECOND job's fresh engines interleaved on the warm cluster (plus
  # batched serving lookups) must also compile nothing, and the
  # stateplane backend-swap phase: a fresh engine under the pallas
  # exchange-rank backend on its own warm (backend-tagged) program
  # keys must compile nothing either. ~25 s on CPU.
  JAX_PLATFORMS=cpu timeout -k 10 300 \
    python tools/recompile_smoke.py || exit 1

  # Serving smoke: 2 concurrent ingesting jobs on one mesh + client
  # threads hammering batched queryable-state lookups through the
  # READ-REPLICA plane and the r19 NATIVE FAST PATH (GIL-free hot-row
  # probe table in native/hotcache.cpp + packed zero-copy batch
  # lookups + session priming). FAILS on any steady-state XLA compile
  # after job-1 warms the shared program cache + replica tier lattice,
  # on a per-job program-cache miss, on lookup p99 over 25 ms, on
  # throughput under 350k lookups/s (raised from 216k when the native
  # fast path landed; measured ~500-580k here at the 5 ms client
  # pause, ~1.1M/s at the bench row's 2 ms point), on the native hit
  # path being < 2x cheaper per hit than the Python dict path
  # (tools/bench_hotcache.py microbench), on replica staleness p99
  # over 1 s (a starved publish loop behind big lookup numbers is a
  # different product), on a packed-vs-dict result mismatch, on a
  # silent fallback to the Python cache while the native library
  # built (SERVING_REQUIRE_NATIVE_HOTCACHE above), on a zero hot-row
  # hit rate / <2 replica generations (vacuity guards), or on a quota
  # violation. ~60 s on CPU.
  SERVING_SMOKE_RECORDS=$((1 << 17)) \
    JAX_PLATFORMS=cpu timeout -k 10 300 \
    python tools/serving_smoke.py || exit 1

  # Frontend smoke: the MULTI-PROCESS serving tier — 2 frontend
  # processes attach the owner's shm hot-cache arenas and serve the
  # hit path in their own processes (seqlock probes over MAP_SHARED,
  # misses crossing to the owner's replica path). Phase 1 fuzzes the
  # cross-process seqlock: readers probe while the owner primes
  # generation after generation — FAILS on ANY torn read surfacing
  # (generation-deterministic value oracle) or a vacuous overlap.
  # Phase 2 runs real ingest + frontend lookup load — FAILS on
  # owner/frontend parity divergence, replica staleness p99 over 2 s,
  # zero frontend shm hits (hit rate must be > 0), or a dead pool.
  # ~15 s on CPU.
  JAX_PLATFORMS=cpu timeout -k 10 300 \
    python tools/frontend_smoke.py || exit 1

  # Lock smoke: the runtime complement of the flint LCK rules — ONE
  # LockSentinel observes every named_lock across a 2-job session
  # cluster + lookup clients (+ the 2-process frontend pool when the
  # native hotcache built), a backend_scope/set_backend churn on the
  # stateplane backend registry, and a get_or_build race on the
  # program cache's once-latch. FAILS on ANY observed lock-order
  # cycle, on a single hold over 2 s (a lock held across a compile or
  # device call — frontend.pipe's by-design IPC wait is exempt), on
  # fewer than 2 DISTINCT locks actually contended (vacuity: the load
  # must produce real cross-thread traffic on this 1-core box), or on
  # any expected lock family showing zero acquisitions (a hot class
  # reverting named_lock to the bare primitive disappears from the
  # sentinel — the unguarded-hit regression). ~30 s on CPU.
  JAX_PLATFORMS=cpu timeout -k 10 300 \
    python tools/lock_smoke.py || exit 1
fi
