"""Streaming-join benchmarks: the Nexmark-style join rows.

Two rows, growing BENCHMARKS.md toward the Nexmark matrix (ROADMAP
item 4 — scenario diversity as a measured table):

- ``nexmark_q8_windowed_join``: person/auction style (Nexmark Q8
  monitors sellers who registered recently): auctions join persons who
  registered within the trailing window — the interval-join
  formulation, run on the device engine (dual keyed slot tables, fused
  device-mode exchange, banded probe program per batch).
- ``interval_join_10m_keys``: the row-5 thrashing shape applied to a
  two-input operator — 10M distinct keys, live rows far above the
  per-shard device budget, so ingest evicts page cohorts and band
  probes serve cold candidates straight from the paged tier.

Methodology matches bench.py: median of post-warm reps (best/all reps
as secondary fields). ``fire_latency_ms`` reports the emit-latency
percentiles — wall time from an arriving batch to its matches
materialized on the host (the two-input analogue of window fire
latency, so the matrix stays comparable). The ``breakdown`` field is
derived from flight-recorder spans — the same spans a captured
Perfetto trace of the run shows, never private driver timers. It
reports span TOTALS (ingest / probe+prune / harvest): the join
engines don't yet emit per-interaction device spans, so no host-prep
split is claimed (the mesh-sessions bench owns that contract).

    BENCH_JOIN_RECORDS=... BENCH_JOIN_REPS=... \
        JAX_PLATFORMS=cpu python tools/bench_joins.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

from flink_tpu.metrics.core import quantile_sorted  # noqa: E402

BATCH = 1 << 15


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def _latency(samples_ms):
    if not samples_ms:
        return None
    samples_ms = sorted(samples_ms)
    return {"p50": quantile_sorted(samples_ms, 0.5),
            "p99": quantile_sorted(samples_ms, 0.99),
            "max": samples_ms[-1], "count": len(samples_ms)}


def _mesh(shards=8):
    import jax

    from flink_tpu.parallel.mesh import make_mesh

    return make_mesh(min(len(jax.devices()), shards))


def _drive(engine, total, num_keys, rate, band_ms, seed):
    """Alternate left/right batches at ``rate`` events/s of event
    time; watermark trails by the band so pruning is live. Returns
    (events, matches, emit-latency samples, wall seconds, breakdown)
    with the breakdown derived from this pass's flight-recorder
    spans."""
    rng = np.random.default_rng(seed)
    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )
    from flink_tpu.observe import flight_recorder as flight

    rec = flight.recorder()
    flight.set_job("bench_joins")
    rec.clear()
    events = matches = 0
    lat = []
    t0 = time.perf_counter()
    t = 0
    while events < total:
        for side, name in ((0, "price"), (1, "rate")):
            n = min(BATCH, max(total - events, 1))
            keys = rng.integers(0, num_keys, n).astype(np.int64)
            ts = t + (np.arange(n, dtype=np.int64) * 1000) // rate
            b0 = time.perf_counter()
            out = engine.process_batch(RecordBatch({
                KEY_ID_FIELD: keys,
                name: rng.random(n).astype(np.float32),
                TIMESTAMP_FIELD: ts,
            }), side)
            m = sum(len(x) for x in out)
            if m:
                lat.append((time.perf_counter() - b0) * 1e3)
            matches += m
            events += n
        t = int(ts[-1]) + 1
        engine.on_watermark(t - band_ms)
    dt = time.perf_counter() - t0
    # span-derived totals, NOT the mesh engines' host-prep breakdown:
    # the join engines don't (yet) emit device.dispatch/fence spans,
    # so a host_prep_s line here would claim their inline device work
    # as host time — report only what the spans actually attribute
    from flink_tpu.observe.export import span_rollup

    breakdown = span_rollup(rec.kind_totals(), dt, {
        "ingest_s": "batch.ingest",
        "probe_fire_s": "fire.dispatch",
        "harvest_s": "fire.harvest",
    })
    return events, matches, lat, dt, breakdown


def bench_q8(scale=1.0, reps=None):
    """Person/auction windowed join: auctions (seller-keyed) join the
    persons who registered in the trailing 10 s window."""
    from flink_tpu.joins import MeshIntervalJoinEngine

    total = int(int(os.environ.get(
        "BENCH_JOIN_RECORDS", 4_000_000)) * scale)
    reps = reps or int(os.environ.get("BENCH_JOIN_REPS", 3))
    num_keys = 100_000          # active sellers
    window_ms = 10_000
    rate = 200_000              # events/s of event time per side

    def make():
        # auctions at t match persons registered in [t - window, t]:
        # persons are input 0, auctions input 1 -> stored persons are
        # probed with band [t - window, t] from the auction side
        return MeshIntervalJoinEngine(
            0, window_ms, mesh=_mesh(),
            capacity_per_shard=1 << 18)

    _drive(make(), min(total, 1 << 20), num_keys, rate, window_ms,
           seed=1)  # warm
    runs = [_drive(make(), total, num_keys, rate, window_ms, seed=1)
            for _ in range(reps)]
    evps = [ev / dt for ev, _, _, dt, _ in runs]
    ev, matches, lat, dt, breakdown = runs[evps.index(_median(evps))]
    return {
        "metric": "nexmark_q8_windowed_join_events_per_sec",
        "value": round(_median(evps), 1),
        "best": round(max(evps), 1),
        "reps": [round(x, 1) for x in evps],
        "unit": "events/s",
        "matches": int(matches),
        "fire_latency_ms": _latency(lat),
        "breakdown": breakdown,
        "shape": (f"person/auction interval join, {num_keys:,} "
                  f"sellers, 10 s trailing window, "
                  f"{rate:,} ev/s/side event time, device-mode "
                  "exchange + banded probe program"),
    }


def bench_interval_10m(scale=1.0, reps=None):
    """The thrashing shape: 10M keys, live rows >> device budget."""
    from flink_tpu.joins import MeshIntervalJoinEngine

    total = int(int(os.environ.get(
        "BENCH_JOIN_RECORDS", 4_000_000)) * scale)
    reps = reps or int(os.environ.get("BENCH_JOIN_REPS", 3))
    num_keys = 10_000_000
    band_ms = 2_000
    rate = 400_000
    budget = 1 << 16            # slots/shard/side vs ~800k live rows

    def make():
        return MeshIntervalJoinEngine(
            -band_ms, band_ms, mesh=_mesh(),
            capacity_per_shard=budget, max_device_slots=budget)

    _drive(make(), min(total, 1 << 20), num_keys, rate, band_ms,
           seed=2)  # warm
    runs = []
    spills = []
    for _ in range(reps):
        eng = make()
        runs.append(_drive(eng, total, num_keys, rate, band_ms,
                           seed=2))
        spills.append(eng.spill_counters())
    evps = [ev / dt for ev, _, _, dt, _ in runs]
    i = evps.index(_median(evps))
    ev, matches, lat, dt, breakdown = runs[i]
    sp = spills[i]
    if os.environ.get("BENCH_JOIN_REQUIRE_SPILL") == "1" and (
            sp["rows_evicted"] == 0 or sp["cold_rows_served"] == 0):
        raise RuntimeError(
            f"vacuous join bench: spill never engaged ({sp})")
    return {
        "metric": "interval_join_10m_keys_events_per_sec",
        "value": round(_median(evps), 1),
        "best": round(max(evps), 1),
        "reps": [round(x, 1) for x in evps],
        "unit": "events/s",
        "matches": int(matches),
        "fire_latency_ms": _latency(lat),
        "breakdown": breakdown,
        "spill": sp,
        "shape": (f"10M distinct keys, +-2 s band at {rate:,} ev/s "
                  f"of event time (~1.6M live rows vs "
                  f"{budget * 8:,} device slots/side) — forced paged "
                  "eviction, cold band candidates served from the "
                  "page tier"),
    }


def main():
    import warnings

    warnings.filterwarnings("ignore")
    # BENCH_JOIN_RECORDS is the one scale knob here — the suite driver
    # (bench_suite._join_rows) already folds BENCH_SUITE_SCALE into it,
    # so reading the suite scale again would apply it twice (the
    # bench_mesh_sessions contract)
    for fn in (bench_q8, bench_interval_10m):
        r = fn(1.0)
        print(json.dumps(r), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
