"""Micro-benchmark: fire vs fire_projected on the real backend."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from flink_tpu.platform import sync_platform

sync_platform()

import numpy as np

from flink_tpu.state.slot_table import SlotTable
from flink_tpu.windowing.aggregates import CountAggregate
from flink_tpu.windowing.fire_projectors import TopKFireProjector

N_KEYS = 100_000
K_SLICES = 5

agg = CountAggregate()
table = SlotTable(agg, capacity=1 << 20)
rng = np.random.default_rng(0)
keys = np.arange(N_KEYS, dtype=np.int64)
for s in range(K_SLICES):
    ns = np.full(N_KEYS, 1000 + s, dtype=np.int64)
    slots = table.lookup_or_insert(keys, ns)
    table.scatter(slots, agg.map_input.__self__.map_input(
        __import__("flink_tpu.core.records", fromlist=["RecordBatch"])
        .RecordBatch.from_pydict({"x": np.ones(N_KEYS)})))

proj = TopKFireProjector("count", k=16)


def timeit(label, fn, reps=10):
    fn()  # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    dt = (time.perf_counter() - t0) / reps * 1e3
    print(f"{label}: {dt:.2f} ms")


kz, matrix = table.build_slice_matrix([1000 + s for s in range(K_SLICES)])
print(f"matrix {matrix.shape}")

timeit("build_slice_matrix", lambda: table.build_slice_matrix(
    [1000 + s for s in range(K_SLICES)]))
timeit("fire (full transfer)", lambda: table.fire(matrix))
timeit("fire_projected(top16)", lambda: table.fire_projected(
    matrix, kz, proj))

# isolate the kernel: no host padding
import jax
import jax.numpy as jnp

wp = 1 << 17
padded = np.zeros((wp, K_SLICES), dtype=np.int32)
padded[: len(kz)] = matrix
jm = jnp.asarray(padded)
fp = agg._fire_project_jit(proj)
ff = agg._fire_jit

timeit("kernel fire only", lambda: jax.block_until_ready(
    ff(table.accs, jm)))
timeit("kernel fire_proj only", lambda: jax.block_until_ready(
    fp(table.accs, jm, len(kz))))

# top_k alone
x = jnp.asarray(rng.random(wp).astype(np.float32))
topk = jax.jit(lambda v: jax.lax.top_k(v, 16))
timeit("lax.top_k(131072, 16)", lambda: jax.block_until_ready(topk(x)))
srt = jax.jit(lambda v: jnp.sort(v))
timeit("jnp.sort(131072)", lambda: jax.block_until_ready(srt(x)))
mx = jax.jit(lambda v: jnp.max(v))
timeit("jnp.max(131072)", lambda: jax.block_until_ready(mx(x)))
