"""Multi-tenant serving smoke: 2 jobs + concurrent lookup load (tier-1).

The executable form of the serving-plane acceptance criteria — since
r17 this gates the READ-REPLICA path (boundary-published snapshots +
host hot-row cache + sharded coalescer workers):

1. **Warm phase** — job-1 runs alone on the session cluster and compiles
   the step-program family (incl. the replica publish/gather tiers).
2. **Measured phase** — a FRESH cluster runs TWO fresh jobs (new engine
   instances, same mesh/layout) under the recompile sentinel while
   client threads hammer batched queryable-state lookups. The run FAILS
   on:
   - ANY steady-state XLA compile (shared program cache + warmed
     replica tier lattice must serve both jobs),
   - per-job program-cache misses > 0,
   - lookup p99 over budget (``SERVING_SMOKE_P99_BUDGET_MS``, default
     25 ms — the replica+cache path must hold it under concurrent
     ingest),
   - throughput under the floor (``SERVING_SMOKE_MIN_LOOKUPS_PER_S``,
     default 350,000/s — raised from 216k when the r19 native fast
     path landed: GIL-free hot-row probe table + packed zero-copy
     batch lookups),
   - the native hit path less than ``SERVING_SMOKE_MIN_HIT_RATIO``
     (default 2x) cheaper per hit than the Python dict path
     (microbenched via tools/bench_hotcache.py after the load phase),
   - the serving plane silently on the Python cache while
     ``SERVING_REQUIRE_NATIVE_HOTCACHE=1`` (tier1.sh exports it when
     the up-front native build succeeded — no vacuous green),
   - hot-row cache hit rate == 0 (vacuity: the cache must actually
     serve),
   - replica generations < 2 (vacuity: boundary publishes must
     actually happen),
   - any quota violation, zero served lookups, empty job output, or a
     packed-vs-dict lookup mismatch (one materialized cross-check).
   ``SERVING_SMOKE_PACKED=0`` forces the dict client path (the
   PR-13-shaped control of the NOTES_r19 walk, gated at the pre-r19
   216k floor); ``FLINK_TPU_NATIVE_HOTCACHE=0`` is the cache-plane
   A/B knob.

Prints a JSON line with ``queryable_lookups_per_s`` — `tools/bench_suite.py`
runs this script at bench scale for the BENCHMARKS.md serving row.

    JAX_PLATFORMS=cpu python tools/serving_smoke.py
    SERVING_SMOKE_RECORDS=... SERVING_SMOKE_CLIENTS=... to scale.
    SERVING_SMOKE_REPLICA=0 measures the legacy live-plane path
    (floor/hit-rate/generation gates auto-disable — the A/B lever the
    NOTES_r17 walk uses).
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

RECORDS = int(os.environ.get("SERVING_SMOKE_RECORDS", 200_000))
CLIENTS = int(os.environ.get("SERVING_SMOKE_CLIENTS", 16))
KEYS = int(os.environ.get("SERVING_SMOKE_KEYS", 4096))
P99_BUDGET_MS = float(os.environ.get("SERVING_SMOKE_P99_BUDGET_MS", 25))
#: packed (zero-copy) client lever — read early: the default floor
#: keys on it (1 = the native fast path; 0 = the PR-13-shaped dict
#: control of the NOTES_r19 walk, gated at the old floor)
PACKED = os.environ.get("SERVING_SMOKE_PACKED", "1") != "0"
#: throughput floor, raised for the r19 native fast path (216k was
#: 3x the pre-replica 72k row; the native hot-row table + packed
#: lookups measured ~500k+ here — 350k keeps scheduler-noise headroom
#: while a regression to the GIL-bound hit path trips it)
MIN_LOOKUPS_PER_S = float(os.environ.get(
    "SERVING_SMOKE_MIN_LOOKUPS_PER_S",
    350_000 if PACKED else 216_000))
#: per-hit-cost gate: the native hit path must stay at least this many
#: times cheaper than the Python dict path on THIS box (microbenched
#: via tools/bench_hotcache.py after the load phase; 0 disables)
MIN_HIT_RATIO = float(os.environ.get(
    "SERVING_SMOKE_MIN_HIT_RATIO", 2.0))
#: exported by tier1.sh when the up-front native build succeeded: the
#: smoke then FAILS if the serving plane silently fell back to the
#: Python cache (no vacuous green on the native gates)
REQUIRE_NATIVE = os.environ.get(
    "SERVING_REQUIRE_NATIVE_HOTCACHE") == "1"
QUOTA_ROWS = int(os.environ.get("SERVING_SMOKE_QUOTA_ROWS", 8192))
#: keys per client request: the serving frontend shape — a fan-in of
#: point lookups amortized into request batches (the recorded 72k row
#: used the same 256-key batches, so the 3x floor is apples-to-apples)
LOOKUP_BATCH = int(os.environ.get("SERVING_SMOKE_LOOKUP_BATCH", 256))
#: client inter-request pause: models request interarrival AND keeps
#: unthrottled client spin from GIL-starving the single scheduler
#: thread (point-lookup mode is implicitly paced by the coalescer's
#: ride-collection window; explicit batches are not)
CLIENT_PAUSE_MS = float(os.environ.get(
    "SERVING_SMOKE_CLIENT_PAUSE_MS", 5.0 if LOOKUP_BATCH > 1 else 0.0))
#: replica A/B lever: 0 = legacy live-plane path (control-queue
#: coalescers only) — the floor and replica vacuity gates disable
REPLICA = os.environ.get("SERVING_SMOKE_REPLICA", "1") != "0"
#: boundary publishes batched under this interval (staleness bound)
PUBLISH_INTERVAL_MS = int(os.environ.get(
    "SERVING_SMOKE_PUBLISH_INTERVAL_MS", 25))
#: replica staleness p99 budget (ms): a client shape that starves the
#: ingest/publish loop can post huge lookup numbers against a frozen
#: replica — that is a DIFFERENT product. The r19 pause sweep showed
#: exactly this: the GIL-held dict path at 2 ms pause reached 724k/s
#: with staleness p99 2.5 s (rejected), the packed path 1.05M/s at
#: 350 ms (accepted). 0 disables.
STALENESS_BUDGET_MS = float(os.environ.get(
    "SERVING_SMOKE_STALENESS_BUDGET_MS", 1000))
#: per-optimization A/B levers (the NOTES_r17 measured walk): hot-row
#: cache capacity (0 = every lookup resolves on the replica) and the
#: serving worker-pool size (1 = one drain loop for all shards)
CACHE_ENTRIES = int(os.environ.get(
    "SERVING_SMOKE_CACHE_ENTRIES", 1 << 18))
WORKERS = int(os.environ.get("SERVING_SMOKE_WORKERS", 2))


def _pipeline(sink):
    from flink_tpu.connectors.sources import DataGenSource
    from flink_tpu.core.config import Configuration
    from flink_tpu.datastream.environment import StreamExecutionEnvironment
    from flink_tpu.runtime.watermarks import WatermarkStrategy
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    from flink_tpu.tenancy.quotas import TenantQuota

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 4096,
        "parallelism.default": 4,
        # the latency tier composes with the serving plane: deadline
        # splitting bounds each ingest dispatch, so a lookup miss batch
        # queued behind the device never waits out a full-batch program
        "latency.fire-deadline-ms": 25,
        "serving.replica": REPLICA,
        "serving.replica.publish-interval-ms": PUBLISH_INTERVAL_MS,
        # spill tier sized to the quota's per-shard slice (so the quota
        # has somewhere to shed and steady state stays under it)
        "state.slot-table.max-device-slots": TenantQuota(
            max_resident_rows=QUOTA_ROWS).per_shard_slots(4),
    }))
    (env.add_source(
        DataGenSource(total_records=RECORDS, num_keys=KEYS,
                      events_per_second_of_eventtime=50_000, seed=13),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("key")
        .window(TumblingEventTimeWindows.of(60_000))
        .sum("value").sink_to(sink))
    return env


def main():
    import warnings

    warnings.filterwarnings("ignore")
    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.metrics.core import quantile_sorted
    from flink_tpu.observe import RecompileSentinel
    from flink_tpu.tenancy.program_cache import PROGRAM_CACHE
    from flink_tpu.tenancy.quotas import TenantQuota
    from flink_tpu.tenancy.session_cluster import SessionCluster

    operator = "window_agg(SumAggregate)"

    def run_with_lookups(cluster, job_names, n_clients):
        """Drive the cluster while client threads hammer lookups;
        returns (elapsed_s, errors, max_generations, staleness_ms[])."""
        stop = threading.Event()
        errors = []
        seen = {"gens": 0}
        staleness = []

        def sampler():
            # replica observability: max generations seen (the jobs
            # unbind their replicas at finish, so read DURING the run)
            # and a staleness reservoir for the p99
            while not stop.is_set():
                g = cluster.serving.replica_generations()
                if g > seen["gens"]:
                    seen["gens"] = g
                staleness.append(
                    cluster.serving.replica_staleness_ms())
                time.sleep(0.01)

        def client(i):
            import numpy as np

            rng = np.random.default_rng(100 + i)
            checked = False
            while not stop.is_set():
                try:
                    job = job_names[i % len(job_names)]
                    if LOOKUP_BATCH > 1 and PACKED and REPLICA:
                        ks = rng.integers(0, KEYS,
                                          LOOKUP_BATCH).tolist()
                        res = cluster.lookup_batch_packed(
                            job, operator, ks)
                        if not checked and i == 0:
                            # materialized cross-check: the packed fast
                            # path must match the dict path (the test
                            # suite pins bit-identity; this catches a
                            # broken wire). A publish can land between
                            # the two calls, so only REPEATED mismatch
                            # counts — one moved boundary does not.
                            for _ in range(5):
                                if res.to_dicts() == \
                                        cluster.lookup_batch(
                                            job, operator, ks):
                                    checked = True
                                    break
                                res = cluster.lookup_batch_packed(
                                    job, operator, ks)
                            else:
                                errors.append(
                                    "packed != dict lookup results")
                                return
                    elif LOOKUP_BATCH > 1:
                        cluster.lookup_batch(
                            job, operator,
                            rng.integers(0, KEYS,
                                         LOOKUP_BATCH).tolist())
                    else:
                        cluster.lookup(job, operator,
                                       int(rng.integers(0, KEYS)))
                except RuntimeError as e:
                    if ("is not serving" in str(e)
                            or "already terminated" in str(e)
                            or "shut down" in str(e)):
                        # clean-shutdown shapes: the plane's unbound-job
                        # error, the executor's terminal control-queue
                        # drain, and the worker-pool shutdown
                        return  # job finished: lookups drain off
                    # any OTHER RuntimeError is a serving-path
                    # regression: swallowing it here would kill every
                    # client early while the gate still printed OK
                    errors.append(f"client {i}: {e!r}")
                    return
                except TimeoutError:
                    errors.append(f"client {i}: lookup timed out")
                    return
                if CLIENT_PAUSE_MS:
                    time.sleep(CLIENT_PAUSE_MS / 1e3)

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True)
                   for i in range(n_clients)]
        threads.append(threading.Thread(target=sampler, daemon=True))
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        cluster.run(timeout_s=600)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        return (time.perf_counter() - t0, errors, seen["gens"],
                staleness)

    # ---- phase 1: job-1 warms the cluster — ingest, fire, serving AND
    # replica publish/gather programs all compile here
    warm = SessionCluster(quantum_records=8192,
                          serving_workers=WORKERS,
                          serving_cache_entries=CACHE_ENTRIES)
    warm.submit(_pipeline(CollectSink()), "job-1")
    run_with_lookups(warm, ["job-1"], 2)

    # ---- phase 2: two FRESH jobs on a fresh cluster + lookup load,
    # zero compiles allowed
    PROGRAM_CACHE.reset_stats()
    cluster = SessionCluster(quantum_records=8192,
                             serving_workers=WORKERS,
                             serving_cache_entries=CACHE_ENTRIES)
    s2, s3 = CollectSink(), CollectSink()
    cluster.submit(_pipeline(s2), "job-2",
                   quota=TenantQuota(max_resident_rows=QUOTA_ROWS))
    cluster.submit(_pipeline(s3), "job-3")
    with RecompileSentinel(max_compiles=0,
                           label="second job on warm cluster") as s:
        elapsed, errors, gens, staleness = run_with_lookups(
            cluster, ["job-2", "job-3"], CLIENTS)

    ok = True
    if errors:
        print(f"FAIL: {errors[:3]}")
        ok = False
    from flink_tpu.tenancy.hot_cache import HotRowCache

    native_cache = not isinstance(cluster.serving.hot_cache,
                                  HotRowCache)
    if REQUIRE_NATIVE and not native_cache:
        print("FAIL: native hotcache built but the serving plane fell "
              "back to the Python cache (vacuous native gates)")
        ok = False
    metrics = cluster.serving.metrics()
    lookups = int(metrics["lookups_total"])
    p99 = float(metrics["lookup_p99_ms"])
    hit_rate = float(metrics["hot_row_hit_rate"])
    staleness_p99 = quantile_sorted(sorted(staleness), 0.99) \
        if staleness else 0.0
    lookups_per_s = lookups / elapsed if elapsed > 0 else 0.0
    for job in ("job-2", "job-3"):
        misses = PROGRAM_CACHE.stats_for(job)["misses"]
        if misses:
            print(f"FAIL: {job} paid {misses} program-cache misses on a "
                  "warm cluster (cache key leaking engine/job identity?)")
            ok = False
    if lookups == 0:
        print("FAIL: zero lookups served — vacuous run")
        ok = False
    if p99 > P99_BUDGET_MS:
        print(f"FAIL: lookup p99 {p99:.1f} ms over the "
              f"{P99_BUDGET_MS:.0f} ms budget")
        ok = False
    if REPLICA:
        if STALENESS_BUDGET_MS and staleness_p99 > STALENESS_BUDGET_MS:
            print(f"FAIL: replica staleness p99 {staleness_p99:.0f} ms "
                  f"over the {STALENESS_BUDGET_MS:.0f} ms budget — "
                  "lookups are outrunning a starved publish loop")
            ok = False
        if lookups_per_s < MIN_LOOKUPS_PER_S:
            print(f"FAIL: {lookups_per_s:,.0f} lookups/s under the "
                  f"{MIN_LOOKUPS_PER_S:,.0f} floor (3x the recorded "
                  "pre-replica row)")
            ok = False
        if hit_rate <= 0.0:
            print("FAIL: hot-row cache never served a hit — the "
                  "replica path is vacuously off")
            ok = False
        if gens < 2:
            print(f"FAIL: replica generations advanced only {gens} "
                  "times — boundary publishes are vacuously off")
            ok = False
    viol = cluster.jobs["job-2"].ledger.quota_violations
    if viol:
        print(f"FAIL: {viol} quota violations on job-2")
        ok = False
    # per-hit-cost gate (after the load phase — it microbenches on the
    # quiet box): the native hit path must beat the Python dict path
    # by the floor ratio, or the fast path silently regressed
    hit_ratio = None
    if MIN_HIT_RATIO and native_cache:
        from tools.bench_hotcache import measure_hit_cost

        cost = measure_hit_cost(rounds=9)
        if cost is None:
            print("FAIL: native cache armed but the microbench found "
                  "no native library")
            ok = False
        else:
            hit_ratio = cost["ratio"]
            if hit_ratio < MIN_HIT_RATIO:
                print(f"FAIL: native hit path only {hit_ratio:.2f}x "
                      f"cheaper than the Python dict path (floor "
                      f"{MIN_HIT_RATIO:.1f}x; native "
                      f"{cost['native_hit_ns']:.0f} ns vs python "
                      f"{cost['python_hit_ns']:.0f} ns)")
                ok = False
    for name, sink in (("job-2", s2), ("job-3", s3)):
        if len(sink.result()) == 0:
            print(f"FAIL: {name} produced no output")
            ok = False
    print(json.dumps({
        "metric": "queryable_lookups_per_s",
        "value": round(lookups_per_s, 1),
        "unit": "lookups/s",
        "shape": f"{CLIENTS} client threads x "
                 f"{'point lookups' if LOOKUP_BATCH == 1 else f'{LOOKUP_BATCH}-key request batches'} "
                 f"against 2 concurrent ingesting jobs "
                 f"({RECORDS} records each, mesh of 4) "
                 f"— read-replica serving plane "
                 f"({'armed' if REPLICA else 'DISARMED: legacy live-plane path'}), "
                 f"native hot-row table "
                 f"{'armed' if native_cache else 'OFF (Python cache)'}"
                 f"{', packed zero-copy lookups' if PACKED and REPLICA else ', dict lookups'}: "
                 f"hot-row hit rate {hit_rate:.3f}, "
                 f"replica staleness p99 {staleness_p99:.1f} ms "
                 f"({gens} generations), p99 {p99:.2f} ms, "
                 f"0 steady-state compiles (compiles={s.compiles})",
    }), flush=True)
    print(f"serving smoke: lookups={lookups} "
          f"batches={int(metrics['lookup_batches_total'])} "
          f"p99={p99:.2f}ms lookups/s={lookups_per_s:,.0f} "
          f"hit_rate={hit_rate:.3f} generations={gens} "
          f"staleness_p99={staleness_p99:.1f}ms "
          f"compiles={s.compiles} quota_violations={viol} "
          f"native_cache={native_cache} "
          f"hit_ratio={hit_ratio if hit_ratio is None else round(hit_ratio, 2)} "
          f"=> {'OK' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
