"""Multi-tenant serving smoke: 2 jobs + concurrent lookup load (tier-1).

The executable form of the tenancy acceptance criteria:

1. **Warm phase** — job-1 runs alone on the session cluster and compiles
   the step-program family.
2. **Measured phase** — a FRESH cluster runs TWO fresh jobs (new engine
   instances, same mesh/layout) under the recompile sentinel while
   client threads hammer batched queryable-state lookups. The run FAILS
   on:
   - ANY steady-state XLA compile (the shared program cache must serve
     both jobs — a cache key leaking engine/job identity compiles per
     job and trips the sentinel),
   - per-job program-cache misses > 0 (the diagnostic twin of the
     sentinel signal),
   - lookup p99 over budget (``SERVING_SMOKE_P99_BUDGET_MS``, default
     500 ms on CPU — the coalescer + batched gather path must hold it
     under concurrent load),
   - any quota violation (job-2 runs under a resident-row quota with a
     spill tier; enforcement must shed, never violate),
   - zero served lookups (a vacuous run must not pass).

Prints a JSON line with ``queryable_lookups_per_s`` — `tools/bench_suite.py`
runs this script at bench scale for the BENCHMARKS.md serving row.

    JAX_PLATFORMS=cpu python tools/serving_smoke.py
    SERVING_SMOKE_RECORDS=... SERVING_SMOKE_CLIENTS=... to scale.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

RECORDS = int(os.environ.get("SERVING_SMOKE_RECORDS", 200_000))
CLIENTS = int(os.environ.get("SERVING_SMOKE_CLIENTS", 8))
KEYS = int(os.environ.get("SERVING_SMOKE_KEYS", 512))
P99_BUDGET_MS = float(os.environ.get("SERVING_SMOKE_P99_BUDGET_MS", 500))
QUOTA_ROWS = int(os.environ.get("SERVING_SMOKE_QUOTA_ROWS", 4096))
#: keys per client request: 1 = coalesced point lookups (the smoke
#: default), >1 = explicit request batches (the high-QPS bench shape —
#: a serving frontend amortizes its fan-in into device batches)
LOOKUP_BATCH = int(os.environ.get("SERVING_SMOKE_LOOKUP_BATCH", 1))
#: client inter-request pause: models request interarrival AND keeps
#: unthrottled client spin from GIL-starving the single scheduler
#: thread (point-lookup mode is implicitly paced by the coalescer's
#: ride-collection window; explicit batches are not)
CLIENT_PAUSE_MS = float(os.environ.get(
    "SERVING_SMOKE_CLIENT_PAUSE_MS", 5.0 if LOOKUP_BATCH > 1 else 0.0))


def _pipeline(sink):
    from flink_tpu.connectors.sources import DataGenSource
    from flink_tpu.core.config import Configuration
    from flink_tpu.datastream.environment import StreamExecutionEnvironment
    from flink_tpu.runtime.watermarks import WatermarkStrategy
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    from flink_tpu.tenancy.quotas import TenantQuota

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 4096,
        "parallelism.default": 4,
        # spill tier sized to the quota's per-shard slice (so the quota
        # has somewhere to shed and steady state stays under it)
        "state.slot-table.max-device-slots": TenantQuota(
            max_resident_rows=QUOTA_ROWS).per_shard_slots(4),
    }))
    (env.add_source(
        DataGenSource(total_records=RECORDS, num_keys=KEYS,
                      events_per_second_of_eventtime=50_000, seed=13),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("key")
        .window(TumblingEventTimeWindows.of(60_000))
        .sum("value").sink_to(sink))
    return env


def main():
    import warnings

    warnings.filterwarnings("ignore")
    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.observe import RecompileSentinel
    from flink_tpu.tenancy.program_cache import PROGRAM_CACHE
    from flink_tpu.tenancy.quotas import TenantQuota
    from flink_tpu.tenancy.session_cluster import SessionCluster

    operator = "window_agg(SumAggregate)"

    def run_with_lookups(cluster, job_names, n_clients):
        """Drive the cluster while client threads hammer lookups;
        returns (elapsed_s, errors)."""
        stop = threading.Event()
        errors = []

        def client(i):
            import numpy as np

            rng = np.random.default_rng(100 + i)
            while not stop.is_set():
                try:
                    job = job_names[i % len(job_names)]
                    if LOOKUP_BATCH > 1:
                        cluster.lookup_batch(
                            job, operator,
                            rng.integers(0, KEYS,
                                         LOOKUP_BATCH).tolist())
                    else:
                        cluster.lookup(job, operator,
                                       int(rng.integers(0, KEYS)))
                except RuntimeError as e:
                    if ("is not serving" in str(e)
                            or "already terminated" in str(e)):
                        # both clean-shutdown shapes: the plane's
                        # unbound-job error and the executor's
                        # terminal control-queue drain
                        return  # job finished: lookups drain off
                    # any OTHER RuntimeError is a serving-path
                    # regression: swallowing it here would kill every
                    # client early while the gate still printed OK
                    errors.append(f"client {i}: {e!r}")
                    return
                except TimeoutError:
                    errors.append(f"client {i}: lookup timed out")
                    return
                if CLIENT_PAUSE_MS:
                    time.sleep(CLIENT_PAUSE_MS / 1e3)

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True)
                   for i in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        cluster.run(timeout_s=600)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        return time.perf_counter() - t0, errors

    # ---- phase 1: job-1 warms the cluster — ingest, fire AND serving
    # programs all compile here (compiles are expected)
    warm = SessionCluster(quantum_records=8192)
    warm.submit(_pipeline(CollectSink()), "job-1")
    run_with_lookups(warm, ["job-1"], 2)

    # ---- phase 2: two FRESH jobs on a fresh cluster + lookup load,
    # zero compiles allowed
    PROGRAM_CACHE.reset_stats()
    cluster = SessionCluster(quantum_records=8192)
    s2, s3 = CollectSink(), CollectSink()
    cluster.submit(_pipeline(s2), "job-2",
                   quota=TenantQuota(max_resident_rows=QUOTA_ROWS))
    cluster.submit(_pipeline(s3), "job-3")
    with RecompileSentinel(max_compiles=0,
                           label="second job on warm cluster") as s:
        elapsed, errors = run_with_lookups(
            cluster, ["job-2", "job-3"], CLIENTS)

    ok = True
    if errors:
        print(f"FAIL: {errors[:3]}")
        ok = False
    metrics = cluster.serving.metrics()
    lookups = int(metrics["lookups_total"])
    p99 = float(metrics["lookup_p99_ms"])
    lookups_per_s = lookups / elapsed if elapsed > 0 else 0.0
    for job in ("job-2", "job-3"):
        misses = PROGRAM_CACHE.stats_for(job)["misses"]
        if misses:
            print(f"FAIL: {job} paid {misses} program-cache misses on a "
                  "warm cluster (cache key leaking engine/job identity?)")
            ok = False
    if lookups == 0:
        print("FAIL: zero lookups served — vacuous run")
        ok = False
    if p99 > P99_BUDGET_MS:
        print(f"FAIL: lookup p99 {p99:.1f} ms over the "
              f"{P99_BUDGET_MS:.0f} ms budget")
        ok = False
    viol = cluster.jobs["job-2"].ledger.quota_violations
    if viol:
        print(f"FAIL: {viol} quota violations on job-2")
        ok = False
    for name, sink in (("job-2", s2), ("job-3", s3)):
        if len(sink.result()) == 0:
            print(f"FAIL: {name} produced no output")
            ok = False
    print(json.dumps({
        "metric": "queryable_lookups_per_s",
        "value": round(lookups_per_s, 1),
        "unit": "lookups/s",
        "shape": f"{CLIENTS} client threads x "
                 f"{'point lookups' if LOOKUP_BATCH == 1 else f'{LOOKUP_BATCH}-key request batches'} "
                 f"against 2 concurrent jobs "
                 f"({RECORDS} records each, mesh of 4) "
                 f"— coalesced device batches "
                 f"(avg {metrics['avg_batch_size']:.1f} lookups/batch), "
                 f"p99 {p99:.1f} ms, 0 steady-state compiles "
                 f"(compiles={s.compiles})",
    }), flush=True)
    print(f"serving smoke: lookups={lookups} "
          f"batches={int(metrics['lookup_batches_total'])} "
          f"p99={p99:.1f}ms compiles={s.compiles} quota_violations={viol} "
          f"=> {'OK' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
