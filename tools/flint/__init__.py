"""flint — TPU-tracing static analysis for the flink_tpu hot path.

The framework's performance claim rests on the ``keyBy -> window ->
aggregate`` loop staying inside compiled XLA programs: one silent host
sync, one tracer leaking into Python control flow, or one jit identity
that varies per call erases the pipelining wins invisibly (no test
fails — throughput just drops 2-5x). flint makes those regressions a
CI failure instead of a benchmark archaeology project.

Six rules:

- **TRC01 host-sync-in-hot-path** — ``.item()``, ``float()/int()/
  bool()`` on device-tainted values, per-array ``np.asarray`` reads and
  ``block_until_ready()`` inside functions reachable from the engines'
  step/dispatch/harvest entry points (call-graph walk rooted at
  ``MeshSessionEngine`` / ``MeshWindowEngine`` / ``SlotTable``).
- **TRC02 tracer-unsafe-control-flow** — Python ``if``/``while`` on
  values data-dependent on jit arguments inside jitted functions.
- **JIT01 unstable-jit-identity** — ``jax.jit``/``pjit`` applied to a
  lambda or loop-local def on a per-call path (recompiles every
  invocation).
- **REG01 fault-point-registry** — every ``chaos.fault_point("name")``
  literal cross-checked against ``flink_tpu.chaos.KNOWN_FAULT_POINTS``
  and the fnmatch patterns used by tests (typos in either direction
  fail).
- **REG02 metric-counter-registry** — spill-counter and metric-group
  name literals consistent between producers (``state/``,
  ``parallel/``) and consumers (``autoscale/``, ``tools/``).
- **NAT01 native-ctypes-signatures** — every function fetched off a
  ``load_native`` CDLL (symbols matching
  ``flink_tpu.native.NATIVE_SYMBOL_PREFIXES``) declares ``argtypes``
  AND ``restype`` before first call; an undeclared ``restype``
  silently truncates 64-bit returns and pointers to C int.

False positives are silenced in place with a reviewed suppression that
MUST carry a reason::

    x.block_until_ready()  # flint: disable=TRC01 -- fence drain is the
                           # pipelining backpressure point

Run ``python -m tools.flint flink_tpu/ --json flint_report.json``.
"""

from tools.flint.cli import main  # noqa: F401
