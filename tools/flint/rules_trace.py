"""TRC01 / TRC02 / JIT01 — the TPU-tracing rules.

Shared machinery: a small forward local-taint pass over a function
body. "Tainted" means *derives from a device value* (TRC01) or *derives
from a jit argument, i.e. is a tracer* (TRC02). The pass is
intentionally simple — straight-line propagation through assignments,
loop targets, comprehensions, subscripts and attribute access, iterated
to a fixpoint — because linter taint must be cheap and predictable, and
anything it cannot see resolves to "untainted" (precision comes from
the reviewed suppressions, recall from the generous device-source
list).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.flint.callgraph import FunctionInfo, PackageIndex
from tools.flint.core import Checker, Project, Violation, register

#: attribute names whose CALL RESULT lives on device: the engines' step
#: programs and jit builders follow a strict naming convention
#: (_*_step / _*_jit / _*_kernel), which this rule locks in
_DEVICE_CALL_SUFFIXES = ("_step", "_jit", "_kernel")
#: attribute/function calls that land values on device
_DEVICE_CALLS = {"device_put", "_put_sharded", "make_fence"}
#: attribute paths that ARE device state
_DEVICE_ATTRS = {"accs"}
#: reading shape metadata off a device value / tracer is trace-time
#: static, never a sync
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "dtypes"}
#: calls whose result is host-side even when fed tainted values (they
#: are the flag points themselves, or sanctioned batched reads)
_SYNC_SINKS = {"asarray", "array", "ascontiguousarray"}


def _attr_chain(node: ast.AST) -> List[str]:
    """['self', 'accs'] for ``self.accs``; [] when not a pure chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


class TaintPass(ast.NodeVisitor):
    """Forward may-taint over one function body (nested defs included —
    they run, if at all, within the enclosing function's extent)."""

    def __init__(self, seeds: Set[str], device_mode: bool):
        #: tainted local names
        self.tainted: Set[str] = set(seeds)
        #: whether device-source CALLS seed taint (TRC01) — TRC02 seeds
        #: only from jit parameters
        self.device_mode = device_mode
        self.changed = False

    # -------------------------------------------------------------- queries

    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            chain = _attr_chain(node)
            if chain and chain[-1] in _STATIC_ATTRS:
                return False
            if self.device_mode and chain and chain[-1] in _DEVICE_ATTRS:
                return True
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            return self._call_is_device(node) or self._call_propagates(node)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.Compare):
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            # tainted iterable -> tainted elements
            return any(self.is_tainted(g.iter) for g in node.generators) \
                or self.is_tainted(node.elt)
        return False

    def _call_is_device(self, call: ast.Call) -> bool:
        if not self.device_mode:
            return False
        fn = call.func
        chain = _attr_chain(fn)
        if not chain:
            return False
        last = chain[-1]
        if last in _DEVICE_CALLS:
            return True
        if any(last.endswith(s) for s in _DEVICE_CALL_SUFFIXES):
            return True
        # jnp.* builds device values; of jax.* only device_put does
        # (jax.devices() / jax.jit(...) etc. return host objects)
        if chain[0] == "jnp":
            return True
        return False

    def _call_propagates(self, call: ast.Call) -> bool:
        """tuple(tainted) / zip(tainted) / enumerate / sorted / .items()
        keep taint; the sync sinks (asarray & friends, the scalar
        casts) return HOST values."""
        fn = call.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        if name in _SYNC_SINKS or name in ("int", "float", "bool", "len",
                                           "device_get", "item",
                                           "block_until_ready"):
            return False
        if name in ("tuple", "list", "zip", "enumerate", "sorted",
                    "reversed", "iter", "next", "items", "values"):
            return any(self.is_tainted(a) for a in call.args) or (
                isinstance(fn, ast.Attribute) and self.is_tainted(fn.value))
        if isinstance(fn, ast.Attribute) and name in ("copy", "astype",
                                                      "reshape", "get"):
            return self.is_tainted(fn.value)
        return False

    # ---------------------------------------------------------- propagation

    def _taint_target(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            if target.id not in self.tainted:
                self.tainted.add(target.id)
                self.changed = True
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._taint_target(e)
        elif isinstance(target, ast.Starred):
            self._taint_target(target.value)

    def visit_Assign(self, node: ast.Assign) -> None:
        if self.is_tainted(node.value):
            for t in node.targets:
                self._taint_target(t)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None and self.is_tainted(node.value):
            self._taint_target(node.target)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if self.is_tainted(node.value):
            self._taint_target(node.target)
        self.generic_visit(node)

    def _taint_loop_target(self, target: ast.AST, it: ast.AST) -> None:
        """zip-aware: ``for a, m in zip(accs, methods)`` taints only the
        targets whose zip operand is tainted — blanket tuple smearing
        would drag closure constants into the tainted set."""
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "zip" \
                and isinstance(target, (ast.Tuple, ast.List)) \
                and len(target.elts) == len(it.args):
            for t, a in zip(target.elts, it.args):
                if self.is_tainted(a):
                    self._taint_target(t)
            return
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "enumerate" and it.args \
                and isinstance(target, (ast.Tuple, ast.List)) \
                and len(target.elts) == 2:
            if self.is_tainted(it.args[0]):
                self._taint_target(target.elts[1])
            return
        if self.is_tainted(it):
            self._taint_target(target)

    def visit_For(self, node: ast.For) -> None:
        self._taint_loop_target(node.target, node.iter)
        self.generic_visit(node)

    def visit_comprehension_generators(self, node) -> None:
        for g in node.generators:
            self._taint_loop_target(g.target, g.iter)

    def visit_ListComp(self, node):
        self.visit_comprehension_generators(node)
        self.generic_visit(node)

    def visit_SetComp(self, node):
        self.visit_comprehension_generators(node)
        self.generic_visit(node)

    def visit_DictComp(self, node):
        self.visit_comprehension_generators(node)
        self.generic_visit(node)

    def visit_GeneratorExp(self, node):
        self.visit_comprehension_generators(node)
        self.generic_visit(node)

    def visit_withitem(self, node):
        if node.optional_vars is not None and self.is_tainted(
                node.context_expr):
            self._taint_target(node.optional_vars)

    def run(self, body: List[ast.stmt]) -> None:
        for _ in range(4):  # tiny fixpoint: chains are short
            self.changed = False
            for stmt in body:
                self.visit(stmt)
            if not self.changed:
                return


def taint_function(node, seeds: Set[str], device_mode: bool) -> TaintPass:
    tp = TaintPass(seeds, device_mode)
    tp.run(node.body)
    return tp


# --------------------------------------------------------------------- TRC01

#: the hot-path entry points: the engines' step/dispatch/harvest
#: surface. Everything transitively callable from here runs per batch,
#: per watermark or per harvest — one host sync stalls the XLA queue.
HOT_ROOTS: Dict[str, Tuple[str, ...]] = {
    "MeshWindowEngine": ("process_batch", "on_watermark"),
    "MeshSessionEngine": ("process_batch", "on_watermark"),
    "SlotTable": ("upsert", "upsert_valued", "scatter", "scatter_valued",
                  "scatter_signed", "fire", "fire_hybrid", "fire_async",
                  "fire_projected", "fire_projected_async", "make_fence"),
    "WindowAggOperator": ("process_batch", "process_watermark",
                          "poll_pending_output"),
    "SessionWindowAggOperator": ("process_batch", "process_watermark"),
    "PendingFire": ("harvest", "ready"),
    # the latency tier's delta-harvest entry points (pane
    # pre-aggregation): combined absorb scatter, one-row delta fires,
    # and the partial refolds all run per batch / per watermark
    "PaneTable": ("scatter_flat", "scatter_combined", "window_flat",
                  "fire_window", "fire_window_async", "fire_partial",
                  "fire_partial_async", "rebuild_window_partials",
                  "release_window_row"),
    "PaneWindower": ("process_batch", "on_watermark"),
    # the two-input join engines (flink_tpu/joins/engine.py): ingest,
    # probe and prune all run per batch / per watermark
    "MeshIntervalJoinEngine": ("process_batch", "on_watermark"),
    "MeshTemporalJoinEngine": ("process_batch", "on_watermark"),
    "JoinEngineBase": ("_ingest", "_probe_banded", "_dispatch_probe",
                       "_make_headroom", "_gather_rows"),
    # the device CEP engine (flink_tpu/cep/mesh_engine.py): ingest
    # staging and the fire walk (slot residency, advance dispatch,
    # decode, match-store put, within-prune) run per batch / per
    # watermark
    "MeshCepEngine": ("process_batch", "on_watermark"),
}

#: module-level hot entry points: the device data plane's per-batch
#: staging and the fused exchange+scatter builder are plain functions
#: (flink_tpu/parallel/shuffle.py), not methods — rooting them
#: EXPLICITLY keeps the fused path guarded even if an engine stops
#: calling through a rooted method (the name-based walk would
#: otherwise silently lose the whole device exchange)
HOT_MODULE_ROOTS: Dict[str, Tuple[str, ...]] = {
    "flink_tpu.parallel.shuffle": (
        "stage_device_exchange",
        "bucket_by_shard",
        "_build_exchange_scatter",
    ),
    # the native session-metadata plane's per-batch sweep entry points:
    # one C call per (engine, batch) for absorb/pop — rooted explicitly
    # so host syncs creeping into their Python halves stay caught even
    # if an engine stops calling through a rooted method
    "flink_tpu.windowing.session_native": (
        "native_absorb",
        "native_pop",
    ),
    # the join kernel builders: their closures ARE the per-batch
    # compiled programs — a host sync creeping into the staging or
    # builder path stalls every probe/ingest (rooting the module
    # functions keeps them guarded even off-method)
    "flink_tpu.joins.kernels": (
        "_build_join_put",
        "_build_join_exchange_put",
        "_build_join_gather",
        "_build_banded_probe",
    ),
    "flink_tpu.joins.side_table": (
        "pair_lower_bound",
    ),
    # the CEP kernel builders: the advance closure IS the per-fire
    # compiled NFA program (scan over events, unrolled over states) —
    # rooted like the join kernel builders so a host sync creeping in
    # stalls flint, not production
    "flink_tpu.cep.kernels": (
        "_build_cep_advance",
        "_build_cep_prune",
    ),
    # the delta-harvest program family (fire + reset fused in one
    # dispatch) — its builder closure IS the per-fire compiled program,
    # rooted explicitly like the join kernel builders
    "flink_tpu.parallel.sharded_windower": (
        "_build_delta_fire_step",
    ),
}


@register
class HostSyncInHotPath(Checker):
    rule = "TRC01"
    title = ("host sync on the hot path: .item()/scalar casts/per-array "
             "reads/block_until_ready reachable from engine step paths")

    def check(self, project: Project) -> Iterator[Violation]:
        files = project.package_files("flink_tpu")
        index = PackageIndex(files)
        reachable = index.reachable(
            {c: list(m) for c, m in HOT_ROOTS.items()},
            module_roots={m: list(f)
                          for m, f in HOT_MODULE_ROOTS.items()})
        for fi in reachable.values():
            tp = taint_function(fi.node, set(), device_mode=True)
            yield from self._scan(fi, tp)

    def _scan(self, fi: FunctionInfo, tp: TaintPass) -> Iterator[Violation]:
        in_loop: Set[int] = set()
        for node in ast.walk(fi.node):
            # a For's iterator expression evaluates ONCE — only the body
            # (and a While's test) repeats
            if isinstance(node, ast.For):
                repeat = node.body + node.orelse
            elif isinstance(node, ast.While):
                repeat = [node.test] + node.body + node.orelse
            else:
                continue
            for part in repeat:
                for sub in ast.walk(part):
                    in_loop.add(id(sub))
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            path = fi.sf.path
            if isinstance(fn, ast.Attribute):
                if name == "block_until_ready":
                    yield Violation(
                        rule=self.rule, path=path, line=node.lineno,
                        col=node.col_offset,
                        message="block_until_ready() on the hot path "
                                "stalls the host behind the device "
                                "queue (reachable from "
                                f"{fi.qualname})")
                    continue
                if name == "item" and not node.args \
                        and tp.is_tainted(fn.value):
                    yield Violation(
                        rule=self.rule, path=path, line=node.lineno,
                        col=node.col_offset,
                        message=".item() on a device value is a "
                                "blocking per-element D2H round-trip "
                                "(reachable from "
                                f"{fi.qualname})")
                    continue
                if name == "device_get" and id(node) in in_loop:
                    yield Violation(
                        rule=self.rule, path=path, line=node.lineno,
                        col=node.col_offset,
                        message="jax.device_get inside a loop pays one "
                                "link round-trip per iteration — batch "
                                "all arrays into ONE device_get "
                                "(reachable from "
                                f"{fi.qualname})")
                    continue
            chain = _attr_chain(fn)
            is_np_read = (name in _SYNC_SINKS
                          and (len(chain) != 2
                               or chain[0] in ("np", "numpy")))
            if is_np_read and node.args \
                    and tp.is_tainted(node.args[0]):
                verb = ("serializes one D2H round-trip per array"
                        if id(node) in in_loop else
                        "synchronously reads a device value")
                yield Violation(
                    rule=self.rule, path=fi.sf.path, line=node.lineno,
                    col=node.col_offset,
                    message=f"np.{name} on a device value {verb} — "
                            "batch via one jax.device_get "
                            f"(reachable from {fi.qualname})")
                continue
            if isinstance(fn, ast.Name) and name in ("int", "float", "bool") \
                    and len(node.args) == 1 \
                    and tp.is_tainted(node.args[0]):
                yield Violation(
                    rule=self.rule, path=fi.sf.path, line=node.lineno,
                    col=node.col_offset,
                    message=f"{name}() on a device value forces a "
                            "blocking host sync (reachable from "
                            f"{fi.qualname})")


# --------------------------------------------------------------------- TRC02

def _jit_decorated(node) -> bool:
    """@jit / @jax.jit / @pjit / @partial(jax.jit, ...) decorators."""
    for dec in getattr(node, "decorator_list", []):
        target = dec
        if isinstance(dec, ast.Call):
            fn = dec.func
            fname = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if fname == "partial" and dec.args:
                target = dec.args[0]
            else:
                target = fn
        chain = _attr_chain(target)
        if chain and chain[-1] in ("jit", "pjit"):
            return True
    return False


def _static_params(node) -> Set[str]:
    """Parameter names marked static in a partial(jax.jit,
    static_argnums/static_argnames=...) decorator — not tracers."""
    out: Set[str] = set()
    args = [a.arg for a in node.args.posonlyargs + node.args.args]
    for dec in getattr(node, "decorator_list", []):
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for v in ast.walk(kw.value):
                    if isinstance(v, ast.Constant) and isinstance(
                            v.value, str):
                        out.add(v.value)
            elif kw.arg == "static_argnums":
                for v in ast.walk(kw.value):
                    if isinstance(v, ast.Constant) and isinstance(
                            v.value, int) and 0 <= v.value < len(args):
                        out.add(args[v.value])
    return out


@register
class TracerUnsafeControlFlow(Checker):
    rule = "TRC02"
    title = ("Python if/while on values data-dependent on jit arguments "
             "inside jitted functions")

    def check(self, project: Project) -> Iterator[Violation]:
        for sf in project.package_files("flink_tpu"):
            if sf.tree is None:
                continue
            #: names jit-wrapped at call sites in this module:
            #: f = jax.jit(g) / return jax.jit(kernel)
            wrapped: Set[str] = set()
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    chain = _attr_chain(node.func)
                    if chain and chain[-1] in ("jit", "pjit") \
                            and node.args \
                            and isinstance(node.args[0], ast.Name):
                        wrapped.add(node.args[0].id)
            for node in ast.walk(sf.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if not (_jit_decorated(node) or node.name in wrapped):
                    continue
                params = {a.arg for a in (node.args.posonlyargs
                                          + node.args.args
                                          + node.args.kwonlyargs)}
                if node.args.vararg:
                    params.add(node.args.vararg.arg)
                # nested defs (shard_map locals) receive tracers too
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) \
                            and sub is not node:
                        params.update(a.arg for a in sub.args.args)
                        if sub.args.vararg:
                            params.add(sub.args.vararg.arg)
                params -= _static_params(node)
                params.discard("self")
                tp = taint_function(node, params, device_mode=False)
                yield from self._scan(sf, node, tp)

    def _scan(self, sf, fn_node, tp: TaintPass) -> Iterator[Violation]:
        for node in ast.walk(fn_node):
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
                kind = "if" if isinstance(node, ast.If) else "while"
            elif isinstance(node, ast.IfExp):
                test, kind = node.test, "conditional expression"
            elif isinstance(node, ast.Assert):
                test, kind = node.test, "assert"
            else:
                continue
            if tp.is_tainted(test):
                yield Violation(
                    rule=self.rule, path=sf.path, line=test.lineno,
                    col=test.col_offset,
                    message=f"Python {kind} on a value data-dependent "
                            f"on jit arguments of {fn_node.name!r} — "
                            "inside jit this either crashes "
                            "(ConcretizationTypeError) or forces a "
                            "trace-time constant; use lax.cond / "
                            "lax.while_loop / jnp.where")


# --------------------------------------------------------------------- JIT01

@register
class UnstableJitIdentity(Checker):
    rule = "JIT01"
    title = ("jax.jit/pjit of a lambda or loop-local def on a per-call "
             "path — a fresh jit identity recompiles every invocation")

    def check(self, project: Project) -> Iterator[Violation]:
        for sf in project.package_files("flink_tpu"):
            if sf.tree is None:
                continue
            yield from self._scan_module(sf)

    def _scan_module(self, sf) -> Iterator[Violation]:
        # classify every node's enclosure: module level / function /
        # loop (a jit at module level runs once; inside a function or
        # loop it runs per call / per iteration)
        enclosure: Dict[int, str] = {}

        def mark(nodes, kind):
            for n in nodes:
                for sub in ast.walk(n):
                    enclosure.setdefault(id(sub), kind)

        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.For, ast.While)):
                mark(node.body + node.orelse, "loop")
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                body = node.body if isinstance(node.body, list) \
                    else [node.body]
                mark(body, "function")

        # local def names per function (jit(local_def) in a loop is the
        # classic recompile-per-iteration bug) + the innermost enclosing
        # function of every node, for the memo-cache exemption below
        local_defs: Set[str] = set()
        enclosing_fn: Dict[int, ast.AST] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) \
                            and sub is not node:
                        local_defs.add(sub.name)
                    # ast.walk is top-down, so later (inner) functions
                    # overwrite outer ones: innermost wins
                    enclosing_fn[id(sub)] = node

        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain or chain[-1] not in ("jit", "pjit"):
                continue
            if not node.args:
                continue
            target = node.args[0]
            where = enclosure.get(id(node))
            # the memoized-builder idiom: a jit(lambda) whose enclosing
            # function stores it through a *CACHE* name runs once per
            # cache key, not per call (SlotTable.make_fence & friends)
            host = enclosing_fn.get(id(node))
            if host is not None and any(
                    isinstance(n, ast.Name) and "CACHE" in n.id
                    for n in ast.walk(host)):
                continue
            if isinstance(target, ast.Lambda) and where is not None:
                yield Violation(
                    rule=self.rule, path=sf.path, line=node.lineno,
                    col=node.col_offset,
                    message="jit(lambda) on a per-call path creates a "
                            "fresh jit identity (new cache entry) every "
                            "evaluation — hoist to module level or "
                            "cache the wrapped callable")
            elif isinstance(target, ast.Name) and where == "loop" \
                    and target.id in local_defs:
                yield Violation(
                    rule=self.rule, path=sf.path, line=node.lineno,
                    col=node.col_offset,
                    message=f"jit({target.id}) inside a loop re-wraps a "
                            "local def per iteration — every wrap is a "
                            "new jit identity and a full recompile")
