"""Concurrency rules: guarded fields, lock order, check-then-act, shm.

The serving/tenancy plane is multi-threaded (MiniCluster scheduler +
lookup clients + replica workers) and multi-process (frontends over the
shm hot-cache arena); its bug history is lost-update counters,
check-then-act races and lock-order hazards found by eye. These rules
make that class of bug a CI failure, same shape as the tracing rules:
pure AST over the package source, never importing it.

- **LCK01 guarded-field discipline** — per class, each ``self._x``
  field's guard lock is INFERRED from where the writes happen: if a
  strict majority of the non-``__init__`` write sites hold one
  ``with self._lock``, that lock is the field's guard (same spirit as
  TRC01's taint rooting — the code's own dominant discipline is the
  spec). Any read or mutation outside the guard is then a violation.
  A module-scope variant covers module-global state under a module
  lock. Private helpers whose every in-class call site holds a lock
  analyze as if holding it (one-level call-site inheritance), so
  ``_absorb``-style extracted bodies don't false-positive.
- **LCK02 lock-order consistency** — a static lock-acquisition graph:
  nodes are ``Class.attr`` / ``module.name`` lock identities, edges
  from lexically nested ``with`` blocks plus calls made while holding
  a lock (callee acquisitions resolved through
  :mod:`tools.flint.callgraph`, transitively). A cycle is a potential
  deadlock, reported with a witness site per leg.
- **LCK03 check-then-act across a release boundary** — within one
  function, guarded state read under one acquisition of a lock and
  written under a SEPARATE acquisition of the same lock: whatever the
  first block learned is stale by the second. Calls into same-scope
  helpers that take the lock count as acquisitions (that is exactly
  the ``backend_scope`` read/restore shape).
- **SHM01 attached-handle write discipline** — scopes that attach to
  the shm hot-cache arena (``hc_attach``) are read-side by contract;
  calling any symbol in the ``HOTCACHE_WRITER_SYMBOLS`` registry
  (``flink_tpu/native/__init__.py``, a literal tuple like
  ``NATIVE_SYMBOL_PREFIXES``) from such a scope is a violation.

Known limits (documented in NOTES_r24.md): guards are per-class
(inherited fields don't unify), ``with`` on a local alias of a lock is
invisible, LCK02's non-``self`` lock expressions resolve by attribute
name within the defining module only, and closures fold into their
enclosing function's lock context.
"""

from __future__ import annotations

import ast
from collections import Counter
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from tools.flint.callgraph import PackageIndex, _module_name
from tools.flint.core import Checker, Project, SourceFile, Violation, register

PACKAGE = "flink_tpu"

#: constructors that make an attribute/global a lock identity
_LOCK_CTORS = ("Lock", "RLock", "Condition", "named_lock")

#: method names that mutate their receiver in place — a call through a
#: guarded field is a WRITE to it (thread-safe queue.put/get stay out)
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "add", "update", "pop", "popleft",
    "popitem", "clear", "remove", "discard", "insert", "setdefault",
    "sort", "reverse",
})

#: attribute calls too generic for the duck-typed call-graph fallback:
#: resolving `.get`/`.put`/`.items` to every same-named method in the
#: package would weld builtin-container use into a spurious lock web
_GENERIC_METHODS = frozenset({
    "get", "put", "pop", "add", "append", "appendleft", "extend",
    "update", "clear", "remove", "discard", "insert", "items", "keys",
    "values", "setdefault", "popleft", "popitem", "start", "join",
    "run", "stop", "close", "wait", "notify", "notify_all", "acquire",
    "release", "locked", "send", "recv", "read", "write", "flush",
    "submit", "result", "set", "is_set", "empty", "full", "qsize",
    "copy", "sort", "index", "count", "encode", "decode", "split",
    "strip", "format", "match", "search", "group", "open", "load",
    "dump", "loads", "dumps", "exists", "mkdir", "unlink", "replace",
})

_NATIVE_INIT = "flink_tpu/native/__init__.py"


# ----------------------------------------------------------------- helpers

def _ctor_name(value: ast.AST) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None)
    return name if name in _LOCK_CTORS else None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _local_names(func: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(local names, global-declared names) of a function, params
    included; nested defs fold in (conservative — a name local to a
    closure shadows the global for the whole extent)."""
    locals_: Set[str] = set()
    globals_: Set[str] = set()
    for n in ast.walk(func):
        if isinstance(n, ast.Global):
            globals_.update(n.names)
        elif isinstance(n, ast.Name) and \
                isinstance(n.ctx, (ast.Store, ast.Del)):
            locals_.add(n.id)
        elif isinstance(n, ast.arg):
            locals_.add(n.arg)
    return locals_ - globals_, globals_


def _literal_str_tuple(sf: SourceFile, name: str):
    """((values, lineno)) of a module-level literal string tuple, or
    (None, None) when absent/non-literal."""
    if sf.tree is None:
        return None, None
    for node in sf.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    if not isinstance(node.value, ast.Tuple):
                        return None, node.lineno
                    vals = []
                    for e in node.value.elts:
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, str):
                            vals.append(e.value)
                        else:
                            return None, node.lineno
                    return tuple(vals), node.lineno
    return None, None


# ------------------------------------------------------------------ models

class _ClassModel:
    __slots__ = ("sf", "module", "node", "name", "methods", "lock_attrs",
                 "scans", "inherited", "guards")

    def __init__(self, sf: SourceFile, module: str, node: ast.ClassDef):
        self.sf = sf
        self.module = module
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.AST] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        #: attr -> canonical attr (Condition(self.lock) aliases to lock)
        self.lock_attrs: Dict[str, str] = {}
        aliases: List[Tuple[str, str]] = []
        for n in ast.walk(node):
            if isinstance(n, ast.Assign):
                ctor = _ctor_name(n.value)
                if ctor is None:
                    continue
                for t in n.targets:
                    a = _self_attr(t)
                    if a is None:
                        continue
                    self.lock_attrs[a] = a
                    if ctor == "Condition" and n.value.args:
                        under = _self_attr(n.value.args[0])
                        if under is not None:
                            aliases.append((a, under))
        for cond_attr, under in aliases:
            if under in self.lock_attrs:
                self.lock_attrs[cond_attr] = under


class _ModuleModel:
    __slots__ = ("sf", "module", "classes", "functions", "lock_globals",
                 "globals", "scans", "inherited", "guards")

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.module = _module_name(sf.path)
        self.classes: List[_ClassModel] = []
        self.functions: Dict[str, ast.AST] = {}
        self.lock_globals: Set[str] = set()
        self.globals: Set[str] = set()
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes.append(_ClassModel(sf, self.module, node))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                is_lock = _ctor_name(getattr(node, "value", None)) \
                    is not None
                for t in targets:
                    if isinstance(t, ast.Name):
                        (self.lock_globals if is_lock
                         else self.globals).add(t.id)


# ---------------------------------------------------------------- scanning

# a lock token: ("self", canonical_attr) | ("g", global_name)
#             | ("other", attr)   # some other object's lock attribute
_Token = Tuple[str, str]


class _Access:
    __slots__ = ("scope", "name", "kind", "node", "held", "regions")

    def __init__(self, scope: str, name: str, kind: str, node: ast.AST,
                 held: FrozenSet[_Token], regions: FrozenSet[int]):
        self.scope = scope        # "field" | "global"
        self.name = name
        self.kind = kind          # "read" | "write" | "aug"
        self.node = node
        self.held = held
        self.regions = regions


class _WithRegion:
    __slots__ = ("rid", "node", "tokens", "parent_held")

    def __init__(self, rid: int, node: ast.AST,
                 tokens: FrozenSet[_Token], parent_held: FrozenSet[_Token]):
        self.rid = rid
        self.node = node
        self.tokens = tokens
        self.parent_held = parent_held


class _FuncScan:
    __slots__ = ("accesses", "withs", "self_calls", "local_calls", "calls")

    def __init__(self):
        self.accesses: List[_Access] = []
        self.withs: List[_WithRegion] = []
        #: (method name, call node, held, regions)
        self.self_calls: List[Tuple[str, ast.Call, FrozenSet[_Token],
                                    FrozenSet[int]]] = []
        #: (module function name, call node, held, regions)
        self.local_calls: List[Tuple[str, ast.Call, FrozenSet[_Token],
                                     FrozenSet[int]]] = []
        #: every call with held context (LCK02 resolves these)
        self.calls: List[Tuple[ast.Call, FrozenSet[_Token]]] = []


class _Scanner:
    """One function's lexical scan: accesses with held-lock context,
    ``with``-lock regions, and call sites."""

    def __init__(self, cls: Optional[_ClassModel], mod: _ModuleModel):
        self.cls = cls
        self.mod = mod
        self.out = _FuncScan()
        self._rid = 0
        self.locals: Set[str] = set()
        self.func_globals: Set[str] = set()

    def scan(self, func: ast.AST) -> _FuncScan:
        self.locals, self.func_globals = _local_names(func)
        empty: FrozenSet = frozenset()
        for stmt in func.body:
            self._visit(stmt, empty, empty)
        return self.out

    # -- lock-expression recognition

    def _lock_token(self, expr: ast.AST) -> Optional[_Token]:
        a = _self_attr(expr)
        if a is not None:
            if self.cls is not None and a in self.cls.lock_attrs:
                return ("self", self.cls.lock_attrs[a])
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.mod.lock_globals and \
                    expr.id not in self.locals:
                return ("g", expr.id)
            return None
        if isinstance(expr, ast.Attribute):
            # another object's lock (co._lock): identity by attr name,
            # resolved to candidate classes by LCK02 only
            return ("other", expr.attr)
        return None

    # -- recording

    def _record(self, scope: str, name: str, kind: str, node: ast.AST,
                held: FrozenSet, regions: FrozenSet) -> None:
        if scope == "field" and self.cls is not None:
            if name in self.cls.lock_attrs or name in self.cls.methods:
                return
        self.out.accesses.append(
            _Access(scope, name, kind, node, held, regions))

    def _field_root(self, expr: ast.AST):
        """(scope, name, slice exprs) when the attribute/subscript
        chain roots at ``self.<name>`` or a module global."""
        slices: List[ast.AST] = []
        cur = expr
        while isinstance(cur, (ast.Attribute, ast.Subscript)):
            if isinstance(cur, ast.Subscript):
                slices.append(cur.slice)
                cur = cur.value
            else:
                if isinstance(cur.value, ast.Name) and \
                        cur.value.id == "self":
                    if self.cls is None:
                        return None
                    return ("field", cur.attr, slices)
                cur = cur.value
        if isinstance(cur, ast.Name) and self.cls is None and \
                cur.id in self.mod.globals and cur.id not in self.locals:
            return ("global", cur.id, slices)
        return None

    # -- traversal

    def _target(self, t: ast.AST, held: FrozenSet, regions: FrozenSet,
                aug: bool = False) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e, held, regions, aug)
            return
        if isinstance(t, ast.Starred):
            self._target(t.value, held, regions, aug)
            return
        root = self._field_root(t)
        if root is not None:
            scope, name, slices = root
            self._record(scope, name, "aug" if aug else "write",
                         t, held, regions)
            for s in slices:
                self._visit(s, held, regions)
            return
        if isinstance(t, ast.Name):
            if self.cls is None and t.id in self.func_globals and \
                    t.id in self.mod.globals:
                self._record("global", t.id, "write", t, held, regions)
            return
        self._visit(t, held, regions)

    def _visit(self, node: ast.AST, held: FrozenSet,
               regions: FrozenSet) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            tokens: List[_Token] = []
            for item in node.items:
                t = self._lock_token(item.context_expr)
                if t is not None and t not in tokens:
                    tokens.append(t)
                self._visit(item.context_expr, held, regions)
            if tokens:
                rid = self._rid
                self._rid += 1
                self.out.withs.append(_WithRegion(
                    rid, node, frozenset(tokens), held))
                held = held | frozenset(tokens)
                regions = regions | {rid}
            for stmt in node.body:
                self._visit(stmt, held, regions)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                self._target(t, held, regions)
            self._visit(node.value, held, regions)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._target(node.target, held, regions)
                self._visit(node.value, held, regions)
            return
        if isinstance(node, ast.AugAssign):
            self._target(node.target, held, regions, aug=True)
            self._visit(node.value, held, regions)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._target(t, held, regions)
            return
        if isinstance(node, ast.Call):
            self.out.calls.append((node, held))
            f = node.func
            handled = False
            if isinstance(f, ast.Attribute):
                sa = _self_attr(f)
                if sa is not None:
                    if self.cls is not None and sa in self.cls.methods:
                        self.out.self_calls.append(
                            (sa, node, held, regions))
                        handled = True
                    elif self.cls is not None and \
                            sa in self.cls.lock_attrs:
                        handled = True   # self._lock.acquire() et al.
                else:
                    root = self._field_root(f.value)
                    if root is not None:
                        scope, name, slices = root
                        kind = "write" if f.attr in _MUTATORS else "read"
                        self._record(scope, name, kind, f.value,
                                     held, regions)
                        for s in slices:
                            self._visit(s, held, regions)
                        handled = True
            elif isinstance(f, ast.Name):
                if f.id not in self.locals and \
                        f.id in self.mod.functions:
                    self.out.local_calls.append(
                        (f.id, node, held, regions))
                    handled = True
            if not handled:
                self._visit(f, held, regions)
            for a in node.args:
                self._visit(a, held, regions)
            for kw in node.keywords:
                self._visit(kw.value, held, regions)
            return
        if isinstance(node, ast.Attribute):
            sa = _self_attr(node)
            if sa is not None:
                if self.cls is not None:
                    self._record("field", sa, "read", node, held, regions)
                return
            self._visit(node.value, held, regions)
            return
        if isinstance(node, ast.Name):
            if self.cls is None and isinstance(node.ctx, ast.Load) and \
                    node.id in self.mod.globals and \
                    node.id not in self.locals:
                self._record("global", node.id, "read", node,
                             held, regions)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # closures fold into the enclosing extent (callgraph idiom)
            for stmt in node.body:
                self._visit(stmt, held, regions)
            return
        if isinstance(node, ast.Lambda):
            self._visit(node.body, held, regions)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child, held, regions)


# ---------------------------------------------------------------- analysis

def _inherited_held(scans: Dict[str, _FuncScan],
                    private_ok) -> Dict[str, FrozenSet[_Token]]:
    """Per function, the lock set every in-scope call site provably
    holds (intersection) — a private helper called only under the lock
    analyzes as holding it. Fixed point over in-scope call edges."""
    callsites: Dict[str, List[Tuple[str, FrozenSet[_Token]]]] = {}
    for caller, scan in scans.items():
        for name, _node, held, _r in scan.self_calls + scan.local_calls:
            if name in scans:
                callsites.setdefault(name, []).append((caller, held))
    inherited = {m: frozenset() for m in scans}
    for _ in range(5):
        changed = False
        for m in scans:
            if not private_ok(m):
                continue
            sites = callsites.get(m)
            if not sites:
                continue
            eff: Optional[FrozenSet[_Token]] = None
            for caller, held in sites:
                s = held | inherited.get(caller, frozenset())
                eff = s if eff is None else (eff & s)
            eff = eff or frozenset()
            if eff != inherited[m]:
                inherited[m] = eff
                changed = True
        if not changed:
            break
    return inherited


def _infer_guards(scans: Dict[str, _FuncScan],
                  inherited: Dict[str, FrozenSet[_Token]],
                  scope: str, token_kind: str,
                  skip_funcs: FrozenSet[str] = frozenset(),
                  ) -> Dict[str, Tuple[_Token, int, int]]:
    """name -> (guard token, guarded write count, total write count)
    by strict majority over non-exempt write sites."""
    writes: Dict[str, List[FrozenSet[_Token]]] = {}
    for m, scan in scans.items():
        if m in skip_funcs:
            continue
        inh = inherited.get(m, frozenset())
        for a in scan.accesses:
            if a.scope != scope or a.kind == "read":
                continue
            writes.setdefault(a.name, []).append(a.held | inh)
    guards: Dict[str, Tuple[_Token, int, int]] = {}
    for name, helds in writes.items():
        total = len(helds)
        counts = Counter(t for h in helds for t in h
                         if t[0] == token_kind)
        if not counts:
            continue
        top = counts.most_common(2)
        token, c = top[0]
        if len(top) > 1 and top[1][1] == c:
            continue   # two locks tie: ambiguous, no inference
        if c * 2 > total:
            guards[name] = (token, c, total)
    return guards


def _is_private_method(name: str) -> bool:
    return name.startswith("_") and not name.startswith("__")


class _Analysis:
    """Shared model of the package: per class and per module, the
    scans, the call-site-inherited holds and the inferred guards.
    Built once per project (LCK01/LCK02/LCK03 all read it)."""

    def __init__(self, project: Project):
        self.modules: List[_ModuleModel] = []
        for sf in project.package_files(PACKAGE):
            if sf.tree is None:
                continue
            mm = _ModuleModel(sf)
            self.modules.append(mm)
            for cm in mm.classes:
                cm.scans = {
                    name: _Scanner(cm, mm).scan(fn)
                    for name, fn in cm.methods.items()}
                cm.inherited = _inherited_held(cm.scans,
                                               _is_private_method)
                cm.guards = _infer_guards(
                    cm.scans, cm.inherited, "field", "self",
                    skip_funcs=frozenset({"__init__"}))
            mm.scans = {
                name: _Scanner(None, mm).scan(fn)
                for name, fn in mm.functions.items()}
            mm.inherited = _inherited_held(mm.scans, _is_private_method)
            mm.guards = _infer_guards(mm.scans, mm.inherited,
                                      "global", "g")


def _analysis(project: Project) -> _Analysis:
    a = getattr(project, "_conc_analysis", None)
    if a is None:
        a = _Analysis(project)
        project._conc_analysis = a
    return a


def _token_str(token: _Token) -> str:
    return f"self.{token[1]}" if token[0] == "self" else token[1]


# ------------------------------------------------------------------- LCK01

@register
class GuardedFieldDiscipline(Checker):
    rule = "LCK01"
    title = ("guarded-field discipline: a field whose writes hold one "
             "lock by strict majority must never be touched without it")

    def check(self, project: Project) -> Iterator[Violation]:
        ana = _analysis(project)
        for mm in ana.modules:
            for cm in mm.classes:
                yield from self._scope(
                    mm.sf, cm.scans, cm.inherited, cm.guards,
                    "field", exempt=frozenset({"__init__"}),
                    owner=cm.name)
            yield from self._scope(
                mm.sf, mm.scans, mm.inherited, mm.guards,
                "global", exempt=frozenset(), owner=mm.module)

    def _scope(self, sf, scans, inherited, guards, scope, exempt,
               owner) -> Iterator[Violation]:
        if not guards:
            return
        for m, scan in scans.items():
            if m in exempt:
                continue
            inh = inherited.get(m, frozenset())
            for a in scan.accesses:
                if a.scope != scope:
                    continue
                g = guards.get(a.name)
                if g is None:
                    continue
                token, c, total = g
                if token in (a.held | inh):
                    continue
                what = "self." + a.name if scope == "field" \
                    else "global " + a.name
                verb = {"read": "read", "write": "written",
                        "aug": "mutated in place"}[a.kind]
                tail = (" — a lost-update race" if a.kind == "aug"
                        else "")
                yield Violation(
                    rule=self.rule, path=sf.path,
                    line=getattr(a.node, "lineno", 1),
                    col=getattr(a.node, "col_offset", 0),
                    message=(
                        f"'{what}' is guarded by "
                        f"'{_token_str(token)}' ({c} of {total} write "
                        f"sites hold it) but is {verb} here in "
                        f"{owner}.{m} without the lock{tail}"))


# ------------------------------------------------------------------- LCK02

def _resolve_guarded(idx: PackageIndex, fi, call: ast.Call):
    """resolve_call with the duck-typed fallback reined in: generic
    container/threading method names never fan out package-wide."""
    f = call.func
    if isinstance(f, ast.Attribute):
        base = f.value
        is_self = isinstance(base, ast.Name) and base.id == "self"
        if not is_self and f.attr in _GENERIC_METHODS:
            return []
        if is_self and f.attr in _GENERIC_METHODS:
            # keep real self-dispatch, drop the duck-typed fallback
            hits = idx._family_methods(fi.cls, f.attr)
            return hits
    return idx.resolve_call(fi, call)


@register
class LockOrderConsistency(Checker):
    rule = "LCK02"
    title = ("lock-order consistency: the static acquisition graph "
             "(nested with blocks + calls under a held lock) must be "
             "acyclic")

    def check(self, project: Project) -> Iterator[Violation]:
        ana = _analysis(project)
        idx = PackageIndex(project.package_files(PACKAGE))

        # scan lookup by callgraph qualname
        by_qual: Dict[str, Tuple[_ModuleModel, Optional[_ClassModel],
                                 str, _FuncScan]] = {}
        for mm in ana.modules:
            for cm in mm.classes:
                for name, scan in cm.scans.items():
                    by_qual[f"{mm.module}:{cm.name}.{name}"] = \
                        (mm, cm, name, scan)
            for name, scan in mm.scans.items():
                by_qual[f"{mm.module}:{name}"] = (mm, None, name, scan)

        def nodes_of(token: _Token, mm: _ModuleModel,
                     cm: Optional[_ClassModel]) -> List[str]:
            if token[0] == "self" and cm is not None:
                return [f"{cm.name}.{token[1]}"]
            if token[0] == "g":
                return [f"{mm.module}.{token[1]}"]
            if token[0] == "other":
                # attr-name resolution within the defining module, the
                # caller's own class excluded (same-class instance
                # pairs are the runtime sentinel's job)
                return [f"{c.name}.{c.lock_attrs[token[1]]}"
                        for c in mm.classes
                        if c is not cm and token[1] in c.lock_attrs]
            return []

        # pass 1: direct acquisitions + lexical nesting edges
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

        def add_edge(a: str, b: str, path: str, line: int,
                     text: str) -> None:
            if a != b and (a, b) not in edges:
                edges[(a, b)] = (path, line, text)

        direct: Dict[str, Set[str]] = {}
        for qual, (mm, cm, name, scan) in by_qual.items():
            inh = (cm.inherited if cm is not None
                   else mm.inherited).get(name, frozenset())
            acq: Set[str] = set()
            inh_nodes = [n for t in inh for n in nodes_of(t, mm, cm)]
            for w in scan.withs:
                toks = list(w.tokens)
                held_nodes = list(inh_nodes)
                for t in w.parent_held:
                    held_nodes.extend(nodes_of(t, mm, cm))
                for i, t in enumerate(toks):
                    t_nodes = nodes_of(t, mm, cm)
                    acq.update(t_nodes)
                    for tn in t_nodes:
                        for hn in held_nodes:
                            add_edge(hn, tn, mm.sf.path, w.node.lineno,
                                     f"'{tn}' acquired while holding "
                                     f"'{hn}'")
                        # multi-item with: earlier items lock first
                        for prev in toks[:i]:
                            for pn in nodes_of(prev, mm, cm):
                                add_edge(pn, tn, mm.sf.path,
                                         w.node.lineno,
                                         f"'{tn}' acquired after "
                                         f"'{pn}' in one with")
            direct[qual] = acq

        # pass 2: transitive acquisitions through the call graph
        acq_all = {q: set(s) for q, s in direct.items()}
        changed = True
        while changed:
            changed = False
            for qual, fi in idx.functions.items():
                rec = by_qual.get(qual)
                if rec is None:
                    continue
                cur = acq_all.setdefault(qual, set())
                for call, _held in rec[3].calls:
                    for callee in _resolve_guarded(idx, fi, call):
                        s = acq_all.get(callee.qualname)
                        if s and not s <= cur:
                            cur |= s
                            changed = True

        # pass 3: call-under-lock edges
        for qual, fi in idx.functions.items():
            rec = by_qual.get(qual)
            if rec is None:
                continue
            mm, cm, name, scan = rec
            inh = (cm.inherited if cm is not None
                   else mm.inherited).get(name, frozenset())
            for call, held in scan.calls:
                hs = held | inh
                if not hs:
                    continue
                held_nodes = [n for t in hs for n in nodes_of(t, mm, cm)]
                if not held_nodes:
                    continue
                for callee in _resolve_guarded(idx, fi, call):
                    for tn in acq_all.get(callee.qualname, ()):
                        for hn in held_nodes:
                            add_edge(hn, tn, mm.sf.path, call.lineno,
                                     f"call to {callee.qualname} "
                                     f"(acquires '{tn}') while "
                                     f"holding '{hn}'")

        # cycles: any edge whose reverse direction is reachable
        adj: Dict[str, Set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
        reported: Set[FrozenSet[str]] = set()
        for (a, b) in sorted(edges):
            back = self._path(adj, b, a)
            if back is None:
                continue
            cyc = frozenset([a] + back)
            if cyc in reported:
                continue
            reported.add(cyc)
            legs = [(a, b)] + list(zip(back, back[1:]))
            parts = []
            for x, y in legs:
                path, line, text = edges[(x, y)]
                parts.append(f"{text} [{path}:{line}]")
            path0, line0, _ = edges[(a, b)]
            ring = " -> ".join([a, b] + back[1:])
            yield Violation(
                rule=self.rule, path=path0, line=line0, col=0,
                message=(f"potential deadlock: lock-order cycle "
                         f"{ring}; " + "; ".join(parts)))

    @staticmethod
    def _path(adj: Dict[str, Set[str]], a: str,
              b: str) -> Optional[List[str]]:
        seen = {a}
        frontier: List[List[str]] = [[a]]
        while frontier:
            p = frontier.pop()
            if p[-1] == b:
                return p
            for nxt in sorted(adj.get(p[-1], ())):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(p + [nxt])
        return None


# ------------------------------------------------------------------- LCK03

class _Region:
    __slots__ = ("line", "reads", "writes", "desc")

    def __init__(self, line: int, reads: Set[str], writes: Set[str],
                 desc: str):
        self.line = line
        self.reads = reads
        self.writes = writes
        self.desc = desc


@register
class CheckThenActAcrossRelease(Checker):
    rule = "LCK03"
    title = ("check-then-act: guarded state read under one lock "
             "acquisition and acted on under a separate one — the "
             "check is stale across the release")

    def check(self, project: Project) -> Iterator[Violation]:
        ana = _analysis(project)
        for mm in ana.modules:
            for cm in mm.classes:
                yield from self._scope(mm.sf, cm.scans, cm.inherited,
                                       cm.guards, cm.name)
            yield from self._scope(mm.sf, mm.scans, mm.inherited,
                                   mm.guards, mm.module)

    def _scope(self, sf, scans, inherited, guards,
               owner) -> Iterator[Violation]:
        if not guards:
            return
        #: lock token -> fields it guards
        by_lock: Dict[_Token, Set[str]] = {}
        for name, (token, _c, _t) in guards.items():
            by_lock.setdefault(token, set()).add(name)

        #: per function: fields read/written while lexically holding L
        def under(scan: _FuncScan, token: _Token, fields: Set[str],
                  kind_read: bool) -> Set[str]:
            out = set()
            for a in scan.accesses:
                if a.name in fields and token in a.held and \
                        (a.kind == "read") == kind_read:
                    out.add(a.name)
            return out

        for fname, scan in scans.items():
            if fname == "__init__":
                continue
            inh = inherited.get(fname, frozenset())
            for token, fields in by_lock.items():
                if token in inh:
                    continue   # whole function runs under the lock
                regions: List[_Region] = []
                # real with-regions (outermost for this lock only)
                for w in scan.withs:
                    if token not in w.tokens or token in w.parent_held:
                        continue
                    reads, writes = set(), set()
                    for a in scan.accesses:
                        if a.name not in fields or \
                                w.rid not in a.regions:
                            continue
                        (reads if a.kind == "read" else writes).add(
                            a.name)
                    regions.append(_Region(
                        w.node.lineno, reads, writes,
                        f"the with block at line {w.node.lineno}"))
                # virtual regions: same-scope calls that take the lock
                for name, node, held, _r in (scan.self_calls +
                                             scan.local_calls):
                    if token in (held | inh):
                        continue
                    callee = scans.get(name)
                    if callee is None:
                        continue
                    reads = under(callee, token, fields, True)
                    writes = under(callee, token, fields, False)
                    if reads or writes:
                        regions.append(_Region(
                            node.lineno, reads, writes,
                            f"the call to {name}() at line "
                            f"{node.lineno}"))
                regions.sort(key=lambda r: r.line)
                seen: Set[Tuple[str, int]] = set()
                for i, r1 in enumerate(regions):
                    for r2 in regions[i + 1:]:
                        if r2.line == r1.line:
                            continue
                        # a second region that RE-READS the field under
                        # its own hold before writing has re-validated
                        # the check (compare-and-restore, drain loops):
                        # not check-then-act
                        for f in sorted((r1.reads & r2.writes)
                                        - r2.reads):
                            key = (f, r2.line)
                            if key in seen:
                                continue
                            seen.add(key)
                            yield Violation(
                                rule=self.rule, path=sf.path,
                                line=r2.line, col=0,
                                message=(
                                    f"check-then-act across a release "
                                    f"boundary in {owner}.{fname}: "
                                    f"'{f}' (guarded by "
                                    f"'{_token_str(token)}') is read "
                                    f"by {r1.desc} but acted on by "
                                    f"{r2.desc} under a separate "
                                    f"acquisition — the lock was "
                                    f"released in between"))


# ------------------------------------------------------------------- SHM01

@register
class AttachedHandleWriteDiscipline(Checker):
    rule = "SHM01"
    title = ("shm write discipline: hotcache writer symbols must never "
             "be called from an hc_attach-rooted (frontend) scope")

    def check(self, project: Project) -> Iterator[Violation]:
        sf = project.get(_NATIVE_INIT)
        if sf is None or sf.tree is None:
            return
        writers, line = _literal_str_tuple(sf, "HOTCACHE_WRITER_SYMBOLS")
        if writers is None:
            yield Violation(
                rule=self.rule, path=_NATIVE_INIT, line=line or 1,
                col=0,
                message=("HOTCACHE_WRITER_SYMBOLS literal string tuple "
                         "is missing from flink_tpu/native/__init__.py "
                         "— SHM01 derives the attach-side deny list "
                         "from it"))
            return
        prefixes, _ = _literal_str_tuple(sf, "NATIVE_SYMBOL_PREFIXES")
        if prefixes:
            for w in writers:
                if not any(w.startswith(p) for p in prefixes):
                    yield Violation(
                        rule=self.rule, path=_NATIVE_INIT, line=line,
                        col=0,
                        message=(f"writer symbol '{w}' matches no "
                                 f"NATIVE_SYMBOL_PREFIXES prefix — "
                                 f"the registry is drifting"))
        writer_set = set(writers)

        def called_symbol(call: ast.Call) -> Optional[str]:
            f = call.func
            if isinstance(f, ast.Attribute):
                return f.attr
            if isinstance(f, ast.Name):
                return f.id
            return None

        for sf2 in project.package_files(PACKAGE):
            if sf2.tree is None:
                continue
            scopes: List[Tuple[str, ast.AST]] = []
            for node in sf2.tree.body:
                if isinstance(node, ast.ClassDef):
                    scopes.append((node.name, node))
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    scopes.append((node.name, node))
            for scope_name, scope_node in scopes:
                calls = [n for n in ast.walk(scope_node)
                         if isinstance(n, ast.Call)]
                attach = [c for c in calls
                          if called_symbol(c) == "hc_attach"]
                if not attach:
                    continue
                for c in calls:
                    s = called_symbol(c)
                    if s in writer_set:
                        yield Violation(
                            rule=self.rule, path=sf2.path,
                            line=c.lineno, col=c.col_offset,
                            message=(
                                f"writer symbol '{s}' called in "
                                f"'{scope_name}', an attach-side scope "
                                f"(hc_attach at line "
                                f"{attach[0].lineno}) — attached shm "
                                f"handles are read-only; writes belong "
                                f"to the owner-side NativeHotRowCache"))
