from tools.flint.cli import main

raise SystemExit(main())
