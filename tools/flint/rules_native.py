"""NAT01 — the ctypes signature rule for native (C ABI) symbols.

Every function fetched off a CDLL returned by ``load_native`` must have
``argtypes`` AND ``restype`` declared before its first call. ctypes
defaults an undeclared ``restype`` to C ``int`` — a 64-bit count or a
pointer silently truncates to 32 bits, the bug class that corrupts at
2^31 rows instead of failing loudly — and undeclared ``argtypes`` let a
Python int pass where a pointer is expected. The native package exports
the canonical symbol-prefix registry (``NATIVE_SYMBOL_PREFIXES``), so
producers (loader declarations) and consumers (call sites anywhere in
the package or tools/) are cross-checked statically against one source,
the same discipline REG01/REG02 apply to fault points and metrics.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from tools.flint.core import Checker, Project, SourceFile, Violation, register

_NATIVE_PKG_FILE = "flink_tpu/native/__init__.py"

#: ctypes attributes that constitute a full declaration
_DECL_ATTRS = ("argtypes", "restype")


def _prefix_registry(sf: SourceFile):
    """(line, prefixes) of the literal NATIVE_SYMBOL_PREFIXES tuple."""
    if sf.tree is None:
        return None
    for node in sf.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) \
                        and t.id == "NATIVE_SYMBOL_PREFIXES" \
                        and isinstance(node.value, (ast.Tuple, ast.List)):
                    vals = []
                    for e in node.value.elts:
                        if isinstance(e, ast.Constant) and isinstance(
                                e.value, str):
                            vals.append(e.value)
                        else:
                            return (node.lineno, tuple())
                    return (node.lineno, tuple(vals))
    return None


@register
class NativeCtypesSignatures(Checker):
    rule = "NAT01"
    title = ("every native symbol fetched off a load_native CDLL "
             "declares argtypes AND restype before first call "
             "(undeclared restype silently truncates to C int)")

    def check(self, project: Project) -> Iterator[Violation]:
        reg_sf = project.get(_NATIVE_PKG_FILE)
        if reg_sf is None:
            yield Violation(
                rule=self.rule, path=_NATIVE_PKG_FILE, line=1, col=0,
                message="native package not found — cannot check ctypes "
                        "signatures")
            return
        parsed = _prefix_registry(reg_sf)
        if parsed is None or not parsed[1]:
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=1, col=0,
                message="no literal NATIVE_SYMBOL_PREFIXES tuple — the "
                        "canonical native-symbol prefix registry must be "
                        "a module-level string tuple here")
            return
        _, prefixes = parsed

        def is_native_sym(name: str) -> bool:
            return name.startswith(prefixes)

        #: sym -> set of declared ctypes attrs, with one decl site
        declared: Dict[str, Set[str]] = {}
        decl_site: Dict[str, Tuple[SourceFile, int, int]] = {}
        #: sym -> call sites
        called: Dict[str, List[Tuple[SourceFile, int, int]]] = {}
        scan = project.package_files("flink_tpu") \
            + project.aux_glob("tools/*.py")
        for sf in scan:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                # declaration: <expr>.<sym>.argtypes = ... / .restype = ...
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and t.attr in _DECL_ATTRS \
                                and isinstance(t.value, ast.Attribute) \
                                and is_native_sym(t.value.attr):
                            sym = t.value.attr
                            declared.setdefault(sym, set()).add(t.attr)
                            decl_site.setdefault(
                                sym, (sf, node.lineno, node.col_offset))
                    continue
                # call: <expr>.<sym>(...)
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and is_native_sym(node.func.attr):
                    called.setdefault(node.func.attr, []).append(
                        (sf, node.lineno, node.col_offset))

        for sym, sites in sorted(called.items()):
            missing = [a for a in _DECL_ATTRS
                       if a not in declared.get(sym, set())]
            if missing:
                sf, line, col = sites[0]
                yield Violation(
                    rule=self.rule, path=sf.path, line=line, col=col,
                    message=f"native symbol {sym!r} is called without "
                            f"{' and '.join(missing)} declared in any "
                            "loader — declare the full ctypes signature "
                            "in the load_* function before first use")
        # partial declarations are latent versions of the same bug even
        # before a call site lands
        for sym, attrs in sorted(declared.items()):
            missing = [a for a in _DECL_ATTRS if a not in attrs]
            if missing:
                sf, line, col = decl_site[sym]
                yield Violation(
                    rule=self.rule, path=sf.path, line=line, col=col,
                    message=f"native symbol {sym!r} declares "
                            f"{sorted(attrs)} but not "
                            f"{' or '.join(missing)} — incomplete ctypes "
                            "signature")
