"""flint command line.

    python -m tools.flint flink_tpu/ [--json flint_report.json]
                                     [--select TRC01,REG01]
                                     [--no-fail] [--verbose]

Exit codes: 0 clean, 1 violations found (gating — the tier-1 default),
2 usage/internal error. ``--fail-on-violation`` names the gating
behavior explicitly for CI scripts; it is already the default.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

# import for side effect: checker registration
from tools.flint import rules_conc  # noqa: F401
from tools.flint import rules_native  # noqa: F401
from tools.flint import rules_registry  # noqa: F401
from tools.flint import rules_trace  # noqa: F401
from tools.flint.core import (
    CHECKERS,
    SUP01_TITLE,
    Project,
    UsageError,
    discover,
    print_human,
    run_checks,
    write_report,
)


def _find_root(paths) -> Path:
    """The repo root: the nearest ancestor of the first target that
    contains the flink_tpu package (aux scans of tests/ and tools/
    resolve against it)."""
    first = Path(paths[0]).resolve()
    probe = first if first.is_dir() else first.parent
    for cand in (probe, *probe.parents):
        if (cand / "flink_tpu" / "__init__.py").is_file():
            return cand
    return Path.cwd()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="flint",
        description="TPU-tracing static analysis for flink_tpu")
    ap.add_argument("paths", nargs="*", default=["flink_tpu/"],
                    help="files or directories to analyze "
                         "(default: flink_tpu/)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the machine-readable report here")
    ap.add_argument("--select", metavar="RULES",
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--rule", metavar="RULE", action="append",
                    default=[],
                    help="run only this rule (repeatable; combines "
                         "with --select)")
    ap.add_argument("--fail-on-violation", action="store_true",
                    help="exit 1 when violations remain (the default; "
                         "spelled out for CI scripts)")
    ap.add_argument("--no-fail", action="store_true",
                    help="always exit 0 (report-only mode)")
    ap.add_argument("--verbose", action="store_true",
                    help="also print suppressed findings with reasons")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in sorted(CHECKERS):
            print(f"{rule}  {CHECKERS[rule].title}")
        print(f"SUP01  {SUP01_TITLE}")
        return 0

    paths = args.paths or ["flink_tpu/"]
    root = _find_root(paths)
    try:
        files = discover(paths, root)
    except UsageError as e:
        print(f"flint: {e}", file=sys.stderr)
        return 2
    if not files:
        print(f"flint: no python files under {paths}", file=sys.stderr)
        return 2
    select = None
    if args.select or args.rule:
        select = [r.strip() for r in (args.select or "").split(",")
                  if r.strip()]
        select += [r.strip() for r in args.rule if r.strip()]
        known = set(CHECKERS) | {"SUP01"}
        unknown = [r for r in select if r not in known]
        if unknown:
            print(f"flint: unknown rule(s) {unknown}; known: "
                  f"{sorted(known)}", file=sys.stderr)
            return 2

    project = Project(files, root)
    timings = {}
    active, suppressed = run_checks(project, select, timings=timings)
    if args.json:
        write_report(args.json, active, suppressed, len(files),
                     timings=timings)
    print_human(active, suppressed, len(files), verbose=args.verbose)
    if active and not args.no_fail:
        return 1
    return 0
