"""Package-wide AST index and a conservative call graph.

Resolution is name-based and deliberately over-approximate — a linter
must never *miss* a reachable host sync, so ambiguity resolves to
"could be called":

- ``self.m(...)`` -> every method named ``m`` in the caller's class
  FAMILY (the inheritance-connected component: the mesh engines call
  through ``MeshSpillSupport`` mixin methods that subclasses override).
- ``obj.m(...)`` on anything else -> every method named ``m`` anywhere
  in the package (duck typing: ``self.windower.on_watermark`` must
  reach all four windower implementations).
- ``f(...)`` -> module-level ``f`` in the same module, else whatever a
  ``from X import f`` in the module points at.
- ``mod.f(...)`` where ``mod``/alias imports a package module -> that
  module's ``f``.

Nested defs and lambdas are folded into their enclosing function: their
bodies execute (if at all) as part of its dynamic extent, and the walk
must see callbacks like ``build`` closures handed to ``PendingFire``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.flint.core import Project, SourceFile


class FunctionInfo:
    __slots__ = ("sf", "module", "cls", "name", "node", "qualname")

    def __init__(self, sf: SourceFile, module: str, cls: Optional[str],
                 name: str, node: ast.AST):
        self.sf = sf
        self.module = module
        self.cls = cls
        self.name = name
        self.node = node
        self.qualname = f"{module}:{cls}.{name}" if cls else f"{module}:{name}"


def _module_name(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class PackageIndex:
    """Functions, classes, imports and inheritance families of one
    package's files."""

    def __init__(self, files: Iterable[SourceFile]):
        #: qualname -> FunctionInfo
        self.functions: Dict[str, FunctionInfo] = {}
        #: method name -> [FunctionInfo] across all classes
        self.methods_by_name: Dict[str, List[FunctionInfo]] = {}
        #: (module, func name) -> FunctionInfo (module level)
        self.module_funcs: Dict[Tuple[str, str], FunctionInfo] = {}
        #: func name -> [FunctionInfo] (module level, all modules)
        self.funcs_by_name: Dict[str, List[FunctionInfo]] = {}
        #: class name -> [class's method dict] (name collisions keep all)
        self.class_methods: Dict[str, List[Dict[str, FunctionInfo]]] = {}
        #: module -> {local alias -> imported module or module:attr}
        self.imports: Dict[str, Dict[str, str]] = {}
        #: class name -> set of class names in its inheritance family
        self.family: Dict[str, Set[str]] = {}

        edges: List[Tuple[str, str]] = []
        for sf in files:
            if sf.tree is None:
                continue
            module = _module_name(sf.path)
            imp = self.imports.setdefault(module, {})
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        imp[a.asname or a.name.split(".")[0]] = a.name
                elif isinstance(node, ast.ImportFrom) and node.module:
                    src = node.module
                    if node.level:  # relative: resolve against module pkg
                        base = module.split(".")[: -node.level]
                        src = ".".join(base + [src]) if base else src
                    for a in node.names:
                        imp[a.asname or a.name] = f"{src}:{a.name}"
            for node in sf.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fi = FunctionInfo(sf, module, None, node.name, node)
                    self.functions[fi.qualname] = fi
                    self.module_funcs[(module, node.name)] = fi
                    self.funcs_by_name.setdefault(node.name, []).append(fi)
                elif isinstance(node, ast.ClassDef):
                    methods: Dict[str, FunctionInfo] = {}
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            fi = FunctionInfo(sf, module, node.name,
                                              item.name, item)
                            self.functions[fi.qualname] = fi
                            methods[item.name] = fi
                            self.methods_by_name.setdefault(
                                item.name, []).append(fi)
                    self.class_methods.setdefault(node.name, []).append(
                        methods)
                    for b in node.bases:
                        base = b.id if isinstance(b, ast.Name) else (
                            b.attr if isinstance(b, ast.Attribute) else None)
                        if base:
                            edges.append((node.name, base))

        # inheritance families: union-find over class-name edges
        parent: Dict[str, str] = {}

        def find(x: str) -> str:
            parent.setdefault(x, x)
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        for a, b in edges:
            ra, rb = find(a), find(b)
            if ra != rb:
                parent[ra] = rb
        groups: Dict[str, Set[str]] = {}
        for cls in set(self.class_methods) | {c for e in edges for c in e}:
            groups.setdefault(find(cls), set()).add(cls)
        for members in groups.values():
            for cls in members:
                self.family[cls] = members

    # ------------------------------------------------------------- resolution

    def _family_methods(self, cls: Optional[str],
                        name: str) -> List[FunctionInfo]:
        if cls is None:
            return []
        out = []
        for member in self.family.get(cls, {cls}):
            for methods in self.class_methods.get(member, []):
                if name in methods:
                    out.append(methods[name])
        return out

    def resolve_call(self, caller: FunctionInfo,
                     call: ast.Call) -> List[FunctionInfo]:
        fn = call.func
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Name) and base.id == "self":
                hits = self._family_methods(caller.cls, fn.attr)
                if hits:
                    return hits
            if isinstance(base, ast.Name):
                target = self.imports.get(caller.module, {}).get(base.id)
                if target and ":" not in target:
                    fi = self.module_funcs.get((target, fn.attr))
                    if fi is not None:
                        return [fi]
            # duck-typed: any method of this name, anywhere
            return list(self.methods_by_name.get(fn.attr, []))
        if isinstance(fn, ast.Name):
            fi = self.module_funcs.get((caller.module, fn.id))
            if fi is not None:
                return [fi]
            target = self.imports.get(caller.module, {}).get(fn.id)
            if target and ":" in target:
                mod, attr = target.split(":", 1)
                fi = self.module_funcs.get((mod, attr))
                if fi is not None:
                    return [fi]
                # from X import Name could be a class: constructor
                for methods in self.class_methods.get(attr, []):
                    if "__init__" in methods:
                        return [methods["__init__"]]
            # class constructor referenced by bare name in-module
            for methods in self.class_methods.get(fn.id, []):
                if "__init__" in methods:
                    return [methods["__init__"]]
        return []

    # ----------------------------------------------------------- reachability

    def reachable(self, roots: Dict[str, Iterable[str]],
                  module_roots: Optional[Dict[str, Iterable[str]]] = None
                  ) -> Dict[str, FunctionInfo]:
        """BFS over the call graph from {class name: [method, ...]}
        roots, plus optional {module: [function, ...]} MODULE-LEVEL
        roots (hot entry points that are plain functions). Returns
        {qualname: FunctionInfo} of every function that can run as part
        of those entry points."""
        frontier: List[FunctionInfo] = []
        for cls, names in roots.items():
            for name in names:
                # exact class only: rooting a family-wide name match
                # would pull every Operator subclass into the walk —
                # `self.m()` dispatch during the BFS still resolves
                # through the whole inheritance family
                for methods in self.class_methods.get(cls, []):
                    if name in methods:
                        frontier.append(methods[name])
        for mod, names in (module_roots or {}).items():
            for name in names:
                fi = self.module_funcs.get((mod, name))
                if fi is not None:
                    frontier.append(fi)
        seen: Dict[str, FunctionInfo] = {}
        while frontier:
            fi = frontier.pop()
            if fi.qualname in seen:
                continue
            seen[fi.qualname] = fi
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    for callee in self.resolve_call(fi, node):
                        if callee.qualname not in seen:
                            frontier.append(callee)
        return seen
