"""REG01 / REG02 — the stringly-typed registry rules.

The codebase carries three name registries that only stay consistent by
convention: chaos fault points, spill counters and metric groups. Each
now has ONE canonical tuple in the package; these rules statically
cross-check every literal producer and consumer against it, so a typo
on either side fails CI instead of silently never injecting / never
reporting.
"""

from __future__ import annotations

import ast
from fnmatch import fnmatchcase
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.flint.core import Checker, Project, SourceFile, Violation, register


def _string_tuple(sf: SourceFile, name: str
                  ) -> Optional[Tuple[int, Tuple[str, ...]]]:
    """(line, values) of a module-level ``NAME = ("a", "b", ...)``
    literal assignment, parsed statically (flint never imports the
    package under analysis)."""
    if sf.tree is None:
        return None
    for node in sf.tree.body:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                if isinstance(value, (ast.Tuple, ast.List)):
                    vals = []
                    for e in value.elts:
                        if isinstance(e, ast.Constant) and isinstance(
                                e.value, str):
                            vals.append(e.value)
                        else:
                            return (node.lineno, tuple())
                    return (node.lineno, tuple(vals))
    return None


def _literal_call_arg(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


# --------------------------------------------------------------------- REG01

_CHAOS_REGISTRY_FILE = "flink_tpu/chaos/__init__.py"
_CHAOS_CALLS = ("fault_point", "io_point", "payload_action")


@register
class FaultPointRegistry(Checker):
    rule = "REG01"
    title = ("chaos fault-point literals cross-checked against "
             "chaos.KNOWN_FAULT_POINTS and test fnmatch patterns")

    def check(self, project: Project) -> Iterator[Violation]:
        reg_sf = project.get(_CHAOS_REGISTRY_FILE)
        known: Set[str] = set()
        reg_line = 1
        if reg_sf is None:
            yield Violation(
                rule=self.rule, path=_CHAOS_REGISTRY_FILE, line=1, col=0,
                message="chaos package not found — cannot check fault "
                        "points")
            return
        parsed = _string_tuple(reg_sf, "KNOWN_FAULT_POINTS")
        if parsed is None:
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=1, col=0,
                message="no literal KNOWN_FAULT_POINTS tuple — the "
                        "canonical fault-point inventory must be a "
                        "module-level string tuple here")
            return
        reg_line, names = parsed
        known = set(names)
        if len(names) != len(known):
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=reg_line, col=0,
                message="KNOWN_FAULT_POINTS contains duplicates")

        # production literals: every chaos.<call>("name") in the package
        produced: Dict[str, List[Tuple[SourceFile, int, int]]] = {}
        for sf in project.package_files("flink_tpu"):
            if sf.tree is None or sf.path == "flink_tpu/chaos/injection.py":
                continue  # the defining module's own docs/plumbing
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr in _CHAOS_CALLS:
                    lit = _literal_call_arg(node)
                    if lit is not None:
                        produced.setdefault(lit, []).append(
                            (sf, node.lineno, node.col_offset))
        for name, sites in sorted(produced.items()):
            if name not in known:
                sf, line, col = sites[0]
                yield Violation(
                    rule=self.rule, path=sf.path, line=line, col=col,
                    message=f"fault point {name!r} is not in "
                            "chaos.KNOWN_FAULT_POINTS — add it to the "
                            "inventory (and NOTES) or fix the typo")
        for name in sorted(known - set(produced)):
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=reg_line, col=0,
                message=f"KNOWN_FAULT_POINTS entry {name!r} has no "
                        "chaos.fault_point/io_point/payload_action call "
                        "site — the injection point went stale")

        # fnmatch patterns used by tests/tools must match something: the
        # universe is the inventory plus any synthetic points the SAME
        # file exercises directly (unit tests of the injection machinery
        # invent points like "a.b")
        for sf in project.aux_glob("tests/*.py") \
                + project.aux_glob("tools/*.py"):
            if sf.tree is None:
                continue
            local_points: Set[str] = set()
            patterns: List[Tuple[str, int, int]] = []
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = node.func.attr if isinstance(
                    node.func, ast.Attribute) else (
                    node.func.id if isinstance(node.func, ast.Name)
                    else "")
                if fname in _CHAOS_CALLS:
                    lit = _literal_call_arg(node)
                    if lit is not None:
                        local_points.add(lit)
                elif fname == "FaultRule":
                    pat = _literal_call_arg(node)
                    if pat is None:
                        for kw in node.keywords:
                            if kw.arg == "pattern" and isinstance(
                                    kw.value, ast.Constant) and isinstance(
                                    kw.value.value, str):
                                pat = kw.value.value
                    if pat is not None:
                        patterns.append((pat, node.lineno,
                                         node.col_offset))
            universe = known | local_points
            for pat, line, col in patterns:
                if not any(fnmatchcase(p, pat) for p in universe):
                    yield Violation(
                        rule=self.rule, path=sf.path, line=line, col=col,
                        message=f"FaultRule pattern {pat!r} matches no "
                                "known fault point — the plan would arm "
                                "and never inject (typo or stale point)")


# --------------------------------------------------------------------- REG02

_COUNTER_REGISTRY_FILE = "flink_tpu/state/paged_spill.py"
_METRIC_REGISTRY_FILE = "flink_tpu/metrics/__init__.py"
#: gauges the executor derives from engine state next to the raw spill
#: counters on the same `state` metric group
_DERIVED_STATE_GAUGES = {"resident_rows", "resident_rows_per_shard",
                         "key_imbalance"}
#: variables treated as spill-counter dicts by naming convention
_COUNTERISH = ("counters", "_ns_counters")


@register
class MetricCounterRegistry(Checker):
    rule = "REG02"
    title = ("spill-counter and metric-group literals consistent with "
             "paged_spill.COUNTER_NAMES / metrics.KNOWN_METRIC_GROUPS")

    def check(self, project: Project) -> Iterator[Violation]:
        yield from self._check_counters(project)
        yield from self._check_groups(project)

    # ------------------------------------------------------------- counters

    def _check_counters(self, project: Project) -> Iterator[Violation]:
        reg_sf = project.get(_COUNTER_REGISTRY_FILE)
        if reg_sf is None:
            return
        parsed = _string_tuple(reg_sf, "COUNTER_NAMES")
        if parsed is None:
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=1, col=0,
                message="no literal COUNTER_NAMES tuple — the canonical "
                        "spill-counter registry must live here")
            return
        _, names = parsed
        known = set(names) | _DERIVED_STATE_GAUGES
        scan = project.package_files("flink_tpu") \
            + project.aux_glob("tools/*.py")
        for sf in scan:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                lit: Optional[str] = None
                if isinstance(node, ast.Subscript) \
                        and self._counterish(node.value) \
                        and isinstance(node.slice, ast.Constant) \
                        and isinstance(node.slice.value, str):
                    lit = node.slice.value
                elif isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr == "get" \
                        and self._counterish(node.func.value):
                    lit = _literal_call_arg(node)
                if lit is not None and lit not in known:
                    yield Violation(
                        rule=self.rule, path=sf.path, line=node.lineno,
                        col=node.col_offset,
                        message=f"spill counter {lit!r} is not in "
                                "paged_spill.COUNTER_NAMES — producers "
                                "and consumers share that one registry")

    @staticmethod
    def _counterish(node: ast.AST) -> bool:
        name = node.id if isinstance(node, ast.Name) else (
            node.attr if isinstance(node, ast.Attribute) else "")
        return any(name == c or name.endswith(c) for c in _COUNTERISH)

    # -------------------------------------------------------------- groups

    def _check_groups(self, project: Project) -> Iterator[Violation]:
        reg_sf = project.get(_METRIC_REGISTRY_FILE)
        if reg_sf is None:
            return
        parsed = _string_tuple(reg_sf, "KNOWN_METRIC_GROUPS")
        if parsed is None:
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=1, col=0,
                message="no literal KNOWN_METRIC_GROUPS tuple — the "
                        "canonical metric-group registry must live here")
            return
        reg_line, names = parsed
        known = set(names)
        produced: Set[str] = set()
        for sf in project.package_files("flink_tpu"):
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr == "add_group":
                    lit = _literal_call_arg(node)
                    if lit is None:  # dynamic names (f-strings) are the
                        continue     # per-operator scopes, out of scope
                    produced.add(lit)
                    if lit not in known:
                        yield Violation(
                            rule=self.rule, path=sf.path,
                            line=node.lineno, col=node.col_offset,
                            message=f"metric group {lit!r} is not in "
                                    "metrics.KNOWN_METRIC_GROUPS — "
                                    "register it or fix the typo")
        for name in sorted(known - produced):
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=reg_line, col=0,
                message=f"KNOWN_METRIC_GROUPS entry {name!r} has no "
                        "add_group producer in the package — stale "
                        "registry entry")
