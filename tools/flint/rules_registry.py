"""REG01 / REG02 / REG03 / REG04 — the stringly-typed registry rules.

The codebase carries five name registries that only stay consistent by
convention: chaos fault points, spill counters, metric groups,
flight-recorder span kinds and compiled program families. Each has ONE
canonical tuple in the package; these rules statically cross-check
every literal producer and consumer against it, so a typo on either
side fails CI instead of silently never injecting / never reporting /
never recording / never sharing an executable.
"""

from __future__ import annotations

import ast
from fnmatch import fnmatchcase
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.flint.core import Checker, Project, SourceFile, Violation, register


def _string_tuple(sf: SourceFile, name: str
                  ) -> Optional[Tuple[int, Tuple[str, ...]]]:
    """(line, values) of a module-level ``NAME = ("a", "b", ...)``
    literal assignment, parsed statically (flint never imports the
    package under analysis)."""
    if sf.tree is None:
        return None
    for node in sf.tree.body:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                if isinstance(value, (ast.Tuple, ast.List)):
                    vals = []
                    for e in value.elts:
                        if isinstance(e, ast.Constant) and isinstance(
                                e.value, str):
                            vals.append(e.value)
                        else:
                            return (node.lineno, tuple())
                    return (node.lineno, tuple(vals))
    return None


def _literal_call_arg(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


# --------------------------------------------------------------------- REG01

_CHAOS_REGISTRY_FILE = "flink_tpu/chaos/__init__.py"
_CHAOS_CALLS = ("fault_point", "io_point", "payload_action")


@register
class FaultPointRegistry(Checker):
    rule = "REG01"
    title = ("chaos fault-point literals cross-checked against "
             "chaos.KNOWN_FAULT_POINTS and test fnmatch patterns")

    def check(self, project: Project) -> Iterator[Violation]:
        reg_sf = project.get(_CHAOS_REGISTRY_FILE)
        known: Set[str] = set()
        reg_line = 1
        if reg_sf is None:
            yield Violation(
                rule=self.rule, path=_CHAOS_REGISTRY_FILE, line=1, col=0,
                message="chaos package not found — cannot check fault "
                        "points")
            return
        parsed = _string_tuple(reg_sf, "KNOWN_FAULT_POINTS")
        if parsed is None:
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=1, col=0,
                message="no literal KNOWN_FAULT_POINTS tuple — the "
                        "canonical fault-point inventory must be a "
                        "module-level string tuple here")
            return
        reg_line, names = parsed
        known = set(names)
        if len(names) != len(known):
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=reg_line, col=0,
                message="KNOWN_FAULT_POINTS contains duplicates")

        # production literals: every chaos.<call>("name") in the package
        produced: Dict[str, List[Tuple[SourceFile, int, int]]] = {}
        for sf in project.package_files("flink_tpu"):
            if sf.tree is None or sf.path == "flink_tpu/chaos/injection.py":
                continue  # the defining module's own docs/plumbing
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr in _CHAOS_CALLS:
                    lit = _literal_call_arg(node)
                    if lit is not None:
                        produced.setdefault(lit, []).append(
                            (sf, node.lineno, node.col_offset))
        for name, sites in sorted(produced.items()):
            if name not in known:
                sf, line, col = sites[0]
                yield Violation(
                    rule=self.rule, path=sf.path, line=line, col=col,
                    message=f"fault point {name!r} is not in "
                            "chaos.KNOWN_FAULT_POINTS — add it to the "
                            "inventory (and NOTES) or fix the typo")
        for name in sorted(known - set(produced)):
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=reg_line, col=0,
                message=f"KNOWN_FAULT_POINTS entry {name!r} has no "
                        "chaos.fault_point/io_point/payload_action call "
                        "site — the injection point went stale")

        # fnmatch patterns used by tests/tools must match something: the
        # universe is the inventory plus any synthetic points the SAME
        # file exercises directly (unit tests of the injection machinery
        # invent points like "a.b")
        for sf in project.aux_glob("tests/*.py") \
                + project.aux_glob("tools/*.py"):
            if sf.tree is None:
                continue
            local_points: Set[str] = set()
            patterns: List[Tuple[str, int, int]] = []
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = node.func.attr if isinstance(
                    node.func, ast.Attribute) else (
                    node.func.id if isinstance(node.func, ast.Name)
                    else "")
                if fname in _CHAOS_CALLS:
                    lit = _literal_call_arg(node)
                    if lit is not None:
                        local_points.add(lit)
                elif fname == "FaultRule":
                    pat = _literal_call_arg(node)
                    if pat is None:
                        for kw in node.keywords:
                            if kw.arg == "pattern" and isinstance(
                                    kw.value, ast.Constant) and isinstance(
                                    kw.value.value, str):
                                pat = kw.value.value
                    if pat is not None:
                        patterns.append((pat, node.lineno,
                                         node.col_offset))
            universe = known | local_points
            for pat, line, col in patterns:
                if not any(fnmatchcase(p, pat) for p in universe):
                    yield Violation(
                        rule=self.rule, path=sf.path, line=line, col=col,
                        message=f"FaultRule pattern {pat!r} matches no "
                                "known fault point — the plan would arm "
                                "and never inject (typo or stale point)")


# --------------------------------------------------------------------- REG02

_COUNTER_REGISTRY_FILE = "flink_tpu/state/paged_spill.py"
_METRIC_REGISTRY_FILE = "flink_tpu/metrics/__init__.py"
#: gauges the executor derives from engine state next to the raw spill
#: counters on the same `state` metric group
_DERIVED_STATE_GAUGES = {"resident_rows", "resident_rows_per_shard",
                         "key_imbalance"}
#: variables treated as spill-counter dicts by naming convention
_COUNTERISH = ("counters", "_ns_counters")


@register
class MetricCounterRegistry(Checker):
    rule = "REG02"
    title = ("spill-counter and metric-group literals consistent with "
             "paged_spill.COUNTER_NAMES / metrics.KNOWN_METRIC_GROUPS")

    def check(self, project: Project) -> Iterator[Violation]:
        yield from self._check_counters(project)
        yield from self._check_groups(project)

    # ------------------------------------------------------------- counters

    def _check_counters(self, project: Project) -> Iterator[Violation]:
        reg_sf = project.get(_COUNTER_REGISTRY_FILE)
        if reg_sf is None:
            return
        parsed = _string_tuple(reg_sf, "COUNTER_NAMES")
        if parsed is None:
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=1, col=0,
                message="no literal COUNTER_NAMES tuple — the canonical "
                        "spill-counter registry must live here")
            return
        _, names = parsed
        known = set(names) | _DERIVED_STATE_GAUGES
        scan = project.package_files("flink_tpu") \
            + project.aux_glob("tools/*.py")
        for sf in scan:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                lit: Optional[str] = None
                if isinstance(node, ast.Subscript) \
                        and self._counterish(node.value) \
                        and isinstance(node.slice, ast.Constant) \
                        and isinstance(node.slice.value, str):
                    lit = node.slice.value
                elif isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr == "get" \
                        and self._counterish(node.func.value):
                    lit = _literal_call_arg(node)
                if lit is not None and lit not in known:
                    yield Violation(
                        rule=self.rule, path=sf.path, line=node.lineno,
                        col=node.col_offset,
                        message=f"spill counter {lit!r} is not in "
                                "paged_spill.COUNTER_NAMES — producers "
                                "and consumers share that one registry")

    @staticmethod
    def _counterish(node: ast.AST) -> bool:
        name = node.id if isinstance(node, ast.Name) else (
            node.attr if isinstance(node, ast.Attribute) else "")
        return any(name == c or name.endswith(c) for c in _COUNTERISH)

    # -------------------------------------------------------------- groups

    def _check_groups(self, project: Project) -> Iterator[Violation]:
        reg_sf = project.get(_METRIC_REGISTRY_FILE)
        if reg_sf is None:
            return
        parsed = _string_tuple(reg_sf, "KNOWN_METRIC_GROUPS")
        if parsed is None:
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=1, col=0,
                message="no literal KNOWN_METRIC_GROUPS tuple — the "
                        "canonical metric-group registry must live here")
            return
        reg_line, names = parsed
        known = set(names)
        produced: Set[str] = set()
        for sf in project.package_files("flink_tpu"):
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr == "add_group":
                    lit = _literal_call_arg(node)
                    if lit is None:  # dynamic names (f-strings) are the
                        continue     # per-operator scopes, out of scope
                    produced.add(lit)
                    if lit not in known:
                        yield Violation(
                            rule=self.rule, path=sf.path,
                            line=node.lineno, col=node.col_offset,
                            message=f"metric group {lit!r} is not in "
                                    "metrics.KNOWN_METRIC_GROUPS — "
                                    "register it or fix the typo")
        for name in sorted(known - produced):
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=reg_line, col=0,
                message=f"KNOWN_METRIC_GROUPS entry {name!r} has no "
                        "add_group producer in the package — stale "
                        "registry entry")


# --------------------------------------------------------------------- REG03

_SPAN_REGISTRY_FILE = "flink_tpu/observe/__init__.py"
_FLIGHT_CALLS = ("span", "instant")
#: call-site convention the rule keys on: the flight recorder is always
#: imported as ``from flink_tpu.observe import flight_recorder as
#: flight`` and used as ``flight.span("kind", ...)``
_FLIGHT_RECEIVER = "flight"


@register
class SpanKindRegistry(Checker):
    rule = "REG03"
    title = ("flight-recorder span-kind literals cross-checked against "
             "observe.KNOWN_SPAN_KINDS")

    @staticmethod
    def _flight_call(node: ast.Call, in_observe: bool) -> Optional[str]:
        """The span-kind literal of a recorder call site, or None.

        Matches ``flight.span("k")`` / ``flight.instant("k")`` (the
        package-wide convention), plus bare ``span("k")`` /
        ``instant("k")`` and ``recorder().span("k")`` inside the
        observe package itself (the defining module and its tests use
        the functions directly). The single-positional-string-literal
        shape keeps ``TraceCollector.span(scope, name)`` — two
        positional args — out of scope."""
        func = node.func
        name = recv = ""
        if isinstance(func, ast.Attribute):
            name = func.attr
            if isinstance(func.value, ast.Name):
                recv = func.value.id
            elif in_observe and isinstance(func.value, ast.Call):
                recv = _FLIGHT_RECEIVER  # recorder().span(...)
        elif isinstance(func, ast.Name):
            name = func.id
            if in_observe:
                recv = _FLIGHT_RECEIVER
        if name not in _FLIGHT_CALLS or recv != _FLIGHT_RECEIVER:
            return None
        if len(node.args) != 1:
            return None
        return _literal_call_arg(node)

    def check(self, project: Project) -> Iterator[Violation]:
        reg_sf = project.get(_SPAN_REGISTRY_FILE)
        if reg_sf is None:
            yield Violation(
                rule=self.rule, path=_SPAN_REGISTRY_FILE, line=1, col=0,
                message="observe package not found — cannot check span "
                        "kinds")
            return
        parsed = _string_tuple(reg_sf, "KNOWN_SPAN_KINDS")
        if parsed is None:
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=1, col=0,
                message="no literal KNOWN_SPAN_KINDS tuple — the "
                        "canonical span-kind inventory must be a "
                        "module-level string tuple here")
            return
        reg_line, names = parsed
        known = set(names)
        if len(names) != len(known):
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=reg_line, col=0,
                message="KNOWN_SPAN_KINDS contains duplicates")
        produced: Set[str] = set()
        scan = project.package_files("flink_tpu") \
            + project.aux_glob("tools/*.py") \
            + project.aux_glob("tests/*.py")
        for sf in scan:
            if sf.tree is None:
                continue
            in_observe = sf.path.startswith("flink_tpu/observe/") \
                or sf.path.startswith("tests/")
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                lit = self._flight_call(node, in_observe)
                if lit is None:
                    continue
                if lit not in known:
                    yield Violation(
                        rule=self.rule, path=sf.path, line=node.lineno,
                        col=node.col_offset,
                        message=f"span kind {lit!r} is not in "
                                "observe.KNOWN_SPAN_KINDS — register "
                                "it (and its exporter category) or fix "
                                "the typo")
                elif sf.path.startswith("flink_tpu/"):
                    produced.add(lit)
        for name in sorted(known - produced):
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=reg_line, col=0,
                message=f"KNOWN_SPAN_KINDS entry {name!r} has no "
                        "flight.span/flight.instant call site in the "
                        "package — the instrumentation point went "
                        "stale")


# --------------------------------------------------------------------- REG04

_FAMILY_REGISTRY_FILE = "flink_tpu/stateplane/families.py"
#: the cache's own module — its docstring/examples mention kinds without
#: producing them
_PROGRAM_CACHE_FILE = "flink_tpu/tenancy/program_cache.py"


@register
class ProgramFamilyRegistry(Checker):
    rule = "REG04"
    title = ("PROGRAM_CACHE family kinds cross-checked against "
             "stateplane.KNOWN_PROGRAM_FAMILIES")

    def check(self, project: Project) -> Iterator[Violation]:
        reg_sf = project.get(_FAMILY_REGISTRY_FILE)
        if reg_sf is None:
            yield Violation(
                rule=self.rule, path=_FAMILY_REGISTRY_FILE, line=1, col=0,
                message="stateplane package not found — cannot check "
                        "program families")
            return
        parsed = _string_tuple(reg_sf, "KNOWN_PROGRAM_FAMILIES")
        if parsed is None:
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=1, col=0,
                message="no literal KNOWN_PROGRAM_FAMILIES tuple — the "
                        "canonical program-family inventory must be a "
                        "module-level string tuple here")
            return
        reg_line, names = parsed
        known = set(names)
        if len(names) != len(known):
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=reg_line, col=0,
                message="KNOWN_PROGRAM_FAMILIES contains duplicates")

        # producers: every <cache>.get_or_build("kind", ...) call in the
        # package whose first argument is a string literal
        produced: Dict[str, List[Tuple[SourceFile, int, int]]] = {}
        for sf in project.package_files("flink_tpu"):
            if sf.tree is None or sf.path == _PROGRAM_CACHE_FILE:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr == "get_or_build":
                    lit = _literal_call_arg(node)
                    if lit is not None:
                        produced.setdefault(lit, []).append(
                            (sf, node.lineno, node.col_offset))
        for name, sites in sorted(produced.items()):
            if name not in known:
                sf, line, col = sites[0]
                yield Violation(
                    rule=self.rule, path=sf.path, line=line, col=col,
                    message=f"program family {name!r} is not in "
                            "stateplane.KNOWN_PROGRAM_FAMILIES — add it "
                            "to the inventory (and the README state-"
                            "plane table) or fix the typo")
        for name in sorted(known - set(produced)):
            yield Violation(
                rule=self.rule, path=reg_sf.path, line=reg_line, col=0,
                message=f"KNOWN_PROGRAM_FAMILIES entry {name!r} has no "
                        "PROGRAM_CACHE.get_or_build call site — the "
                        "program family went stale")
