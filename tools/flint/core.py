"""flint framework: source model, suppressions, checker registry, report.

A checker is a class with a ``rule`` id and a ``check(project)``
generator of :class:`Violation`. The framework owns everything else:
file discovery, AST parsing, the suppression protocol
(``# flint: disable=<RULE>[,<RULE>...] -- <reason>`` — the reason is
MANDATORY; a bare disable is itself a violation), human/JSON output and
exit-code gating.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
import sys
import time
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

#: directive grammar; the reason separator is a literal " -- " so rule
#: lists and prose never ambiguate
_DIRECTIVE = re.compile(
    r"#\s*flint:\s*disable=(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
    r"(?:\s+--\s*(?P<reason>\S.*))?")

#: a line that is nothing but (indentation +) comment: its directives
#: apply to the next source line, so long reasons can sit above the code
_COMMENT_ONLY = re.compile(r"^\s*#")


@dataclasses.dataclass
class Violation:
    rule: str
    path: str              # repo-relative, forward slashes
    line: int              # 1-based
    col: int               # 0-based (ast convention)
    message: str
    suppressed: bool = False
    reason: str = ""

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: " \
               f"{self.rule} {self.message}{tag}"

    def to_json(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class Suppressions:
    """Per-file map of line -> {rule -> reason | None}.

    A directive on a code line covers that line; a directive on a
    comment-only line covers the next non-comment-only line (comment
    blocks stack — every directive line in the block covers the same
    target line).
    """

    def __init__(self, lines: List[str]):
        self.by_line: Dict[int, Dict[str, Optional[str]]] = {}
        self.directive_lines: List[Tuple[int, List[str], Optional[str]]] = []
        pending: List[Tuple[int, List[str], Optional[str]]] = []
        for i, text in enumerate(lines, start=1):
            m = _DIRECTIVE.search(text)
            if m:
                rules = [r.strip() for r in m.group("rules").split(",")]
                reason = m.group("reason")
                self.directive_lines.append((i, rules, reason))
                if _COMMENT_ONLY.match(text):
                    pending.append((i, rules, reason))
                    continue
                self._apply(i, rules, reason)
            if not _COMMENT_ONLY.match(text) and text.strip():
                for _, rules, reason in pending:
                    self._apply(i, rules, reason)
                pending = []
        # trailing comment-only directives cover nothing; keep them in
        # directive_lines so the no-reason check still sees them

    def _apply(self, line: int, rules: List[str],
               reason: Optional[str]) -> None:
        slot = self.by_line.setdefault(line, {})
        for r in rules:
            slot[r] = reason

    def lookup(self, rule: str, line: int) -> Tuple[bool, Optional[str]]:
        slot = self.by_line.get(line)
        if slot is None or rule not in slot:
            return False, None
        return True, slot[rule]


class SourceFile:
    def __init__(self, abspath: Path, relpath: str):
        self.abspath = abspath
        self.path = relpath
        self.text = abspath.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(
                self.text, filename=str(abspath))
        except SyntaxError as e:  # surfaced as a PARSE violation
            self.tree = None
            self.parse_error = e
        self.suppressions = Suppressions(self.lines)


class Project:
    """The files under analysis plus the repo root for aux scans
    (checkers that need tests/ or tools/ regardless of the target)."""

    def __init__(self, files: List[SourceFile], root: Path):
        self.files = files
        self.root = root
        self._by_path = {f.path: f for f in files}
        self._aux_cache: Dict[str, Optional[SourceFile]] = {}

    def get(self, relpath: str) -> Optional[SourceFile]:
        """A file by repo-relative path — from the target set if
        present, else parsed on demand from the repo root (aux file)."""
        if relpath in self._by_path:
            return self._by_path[relpath]
        if relpath not in self._aux_cache:
            p = self.root / relpath
            self._aux_cache[relpath] = (
                SourceFile(p, relpath) if p.is_file() else None)
        return self._aux_cache[relpath]

    def aux_glob(self, pattern: str) -> List[SourceFile]:
        out = []
        for p in sorted(self.root.glob(pattern)):
            if p.is_file() and p.suffix == ".py":
                rel = p.relative_to(self.root).as_posix()
                sf = self.get(rel)
                if sf is not None:
                    out.append(sf)
        return out

    def package_files(self, package: str = "flink_tpu") -> List[SourceFile]:
        """Every file of the named package: target files under the
        package plus any the target set is missing (a partial-target run
        must still see the whole package for cross-file rules)."""
        seen = {f.path for f in self.files if f.path.startswith(package + "/")}
        out = [f for f in self.files if f.path.startswith(package + "/")]
        for p in sorted((self.root / package).rglob("*.py")):
            rel = p.relative_to(self.root).as_posix()
            if rel not in seen:
                sf = self.get(rel)
                if sf is not None:
                    out.append(sf)
        return out


# ------------------------------------------------------------------ registry

CHECKERS: Dict[str, type] = {}


def register(cls):
    """Class decorator: adds the checker to the global registry."""
    rule = getattr(cls, "rule", None)
    if not rule:
        raise ValueError(f"checker {cls.__name__} has no rule id")
    if rule in CHECKERS:
        raise ValueError(f"duplicate checker rule {rule}")
    CHECKERS[rule] = cls
    return cls


class Checker:
    rule: str = ""
    title: str = ""

    def check(self, project: Project) -> Iterator[Violation]:
        raise NotImplementedError


# -------------------------------------------------------------------- runner

class UsageError(Exception):
    """Bad invocation (nonexistent target, ...) — exit 2, not a crash."""


def discover(paths: Iterable[str], root: Path) -> List[SourceFile]:
    files: List[SourceFile] = []
    seen = set()
    for raw in paths:
        p = Path(raw)
        if not p.is_absolute():
            p = root / p
        if p.is_dir():
            candidates = sorted(p.rglob("*.py"))
        elif not p.is_file():
            raise UsageError(f"no such file or directory: {raw}")
        else:
            candidates = [p]
        for c in candidates:
            if "__pycache__" in c.parts or c.suffix != ".py":
                continue
            try:
                rel = c.resolve().relative_to(root).as_posix()
            except ValueError:
                rel = c.as_posix()
            if rel in seen:
                continue
            seen.add(rel)
            files.append(SourceFile(c, rel))
    return files


def run_checks(project: Project,
               select: Optional[Iterable[str]] = None,
               timings: Optional[Dict[str, float]] = None
               ) -> Tuple[List[Violation], List[Violation]]:
    """Returns (active_violations, suppressed_violations). Pass a dict
    as ``timings`` to get per-rule wall seconds back (the gate on the
    conc rules' call-graph pass not silently bloating tier-1)."""
    active: List[Violation] = []
    suppressed: List[Violation] = []

    # parse failures gate everything (an unparsable file is unanalyzed)
    for f in project.files:
        if f.parse_error is not None:
            active.append(Violation(
                rule="PARSE", path=f.path,
                line=f.parse_error.lineno or 1, col=0,
                message=f"syntax error: {f.parse_error.msg}"))

    rules = sorted(CHECKERS) if select is None else [
        r for r in sorted(CHECKERS) if r in set(select)]
    for rule in rules:
        t0 = time.monotonic()
        checker = CHECKERS[rule]()
        for v in checker.check(project):
            sf = project.get(v.path)
            if sf is None:
                active.append(v)
                continue
            hit, reason = sf.suppressions.lookup(v.rule, v.line)
            if hit:
                v.suppressed = True
                v.reason = reason or ""
                suppressed.append(v)
            else:
                active.append(v)
        if timings is not None:
            timings[rule] = round(time.monotonic() - t0, 6)

    # the suppression protocol itself: every directive needs a reason,
    # and directives naming unknown rules are dead weight (typo guard)
    if select is None or "SUP01" in set(select):
        for f in project.files:
            for line, rules_, reason in f.suppressions.directive_lines:
                if reason is None:
                    active.append(Violation(
                        rule="SUP01", path=f.path, line=line, col=0,
                        message="suppression without a reason — write "
                                "'# flint: disable=<RULE> -- <why>'"))
                for r in rules_:
                    if r not in CHECKERS and r != "PARSE":
                        active.append(Violation(
                            rule="SUP01", path=f.path, line=line, col=0,
                            message=f"suppression names unknown rule "
                                    f"{r!r} (known: "
                                    f"{', '.join(sorted(CHECKERS))})"))

    key = (lambda v: (v.path, v.line, v.col, v.rule))
    return sorted(active, key=key), sorted(suppressed, key=key)


#: the framework's built-in rule (suppression protocol) — not a
#: Checker subclass, but selectable and reported like one
SUP01_TITLE = ("suppression protocol: every '# flint: disable' needs "
               "' -- <reason>' and must name known rules")


def write_report(path: str, active: List[Violation],
                 suppressed: List[Violation], files: int,
                 timings: Optional[Dict[str, float]] = None) -> None:
    report = {
        "tool": "flint",
        "checked_files": files,
        "rules": {**{r: CHECKERS[r].title for r in sorted(CHECKERS)},
                  "SUP01": SUP01_TITLE},
        "rule_times_s": dict(sorted((timings or {}).items())),
        "violations": [v.to_json() for v in active],
        "suppressed": [v.to_json() for v in suppressed],
    }
    Path(path).write_text(json.dumps(report, indent=2) + "\n",
                          encoding="utf-8")


def print_human(active: List[Violation], suppressed: List[Violation],
                files: int, verbose: bool = False,
                out=sys.stdout) -> None:
    for v in active:
        print(v.format(), file=out)
    if verbose:
        for v in suppressed:
            print(v.format() + f" [reason: {v.reason}]", file=out)
    print(f"flint: {files} files, {len(active)} violation(s), "
          f"{len(suppressed)} suppressed", file=out)
