"""Capture a flight-recorder trace and write it as Perfetto-loadable
Chrome trace-event JSON.

Two capture shapes:

- ``--shape mesh_sessions`` (default): drive the mesh-sessions bench
  shape (``tools/bench_mesh_sessions.run`` — the row-5 thrashing shape,
  scaled by ``--records``) and dump the pass's spans. The trace shows
  the full per-batch hierarchy: ``batch.ingest`` with
  ``prep.meta_sweep`` / ``prep.stage`` / ``device.dispatch`` /
  ``device.fence_wait`` under it, ``fire.dispatch`` with per-shard
  ``fire.shard`` tracks, coalesced ``fire.harvest`` spans, and any
  ``xla.compile`` / ``d2h.transfer`` / ``watchdog.miss`` /
  ``chaos.inject`` instants on the same clock.
- ``--shape pipeline``: run a small end-to-end executor job
  (source -> keyBy -> session window -> sink, checkpointing every
  batch), so the executor-level spans (``op.process`` /
  ``op.watermark`` / ``emit`` / ``checkpoint.write``) appear too.

Open the output at https://ui.perfetto.dev (or chrome://tracing). One
pid per job, one tid per shard; shard-less spans ride the "host" track.

    JAX_PLATFORMS=cpu python tools/trace_capture.py --out /tmp/trace.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()


def capture_mesh_sessions(records: int) -> None:
    import jax

    from flink_tpu.parallel.mesh import make_mesh
    from tools.bench_mesh_sessions import run

    mesh = make_mesh(min(len(jax.devices()), 8))
    run(min(records, 1 << 20), mesh)   # warm: compiles stay out of the
    run(records, mesh)                 # captured steady-state pass


def capture_pipeline(records: int) -> None:
    from flink_tpu.core.config import Configuration
    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.datastream.environment import (
        StreamExecutionEnvironment,
    )
    from flink_tpu.windowing.assigners import EventTimeSessionWindows

    conf = Configuration({
        "state.checkpoints.dir": "/tmp/flink-tpu-trace-capture-ckpt",
        "execution.checkpointing.every-n-source-batches": 4,
    })
    env = StreamExecutionEnvironment(conf)
    rows = [{"k": i % 64, "v": 1, "ts": i * 7} for i in range(records)]
    env.from_collection(rows, timestamp_field="ts") \
        .key_by("k").window(EventTimeSessionWindows.with_gap(50)) \
        .sum("v").sink_to(CollectSink())
    env.execute("trace-capture")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="/tmp/flink_tpu_trace.json")
    ap.add_argument("--shape", default="mesh_sessions",
                    choices=("mesh_sessions", "pipeline"))
    ap.add_argument("--records", type=int,
                    default=int(os.environ.get("TRACE_CAPTURE_RECORDS",
                                               1 << 20)))
    args = ap.parse_args()

    import warnings

    warnings.filterwarnings("ignore")
    from flink_tpu.observe import install_probes
    from flink_tpu.observe import flight_recorder as flight
    from flink_tpu.observe.export import write_chrome_trace

    if not flight.enabled():
        print("flight recorder is disabled "
              "(FLINK_TPU_FLIGHT_RECORDER=0) — nothing to capture",
              file=sys.stderr)
        return 1
    install_probes()
    if args.shape == "mesh_sessions":
        capture_mesh_sessions(args.records)
    else:
        capture_pipeline(args.records)
    rec = flight.recorder()
    n = write_chrome_trace(args.out, rec)
    kinds = sorted(rec.kind_totals())
    print(json.dumps({
        "trace": args.out,
        "events": n,
        "dropped_oldest": rec.dropped(),
        "span_kinds": kinds,
        "open_with": "https://ui.perfetto.dev",
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
