# Makes `python -m tools.flint` resolvable from the repo root.
