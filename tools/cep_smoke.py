"""CEP smoke (tier-1 gate): the device-vectorized mesh NFA engine
against the host ``CepOperator`` oracle.

FAILS on:
- ORACLE DIVERGENCE: any emitted match differing — bit-for-bit,
  INCLUDING emission order — between the device engine and the host
  backend, for a 3-stage within-window sequence under BOTH after-match
  skip strategies (dense key space) and for an always-alive two-stage
  pattern under FORCED paged eviction (live key set >> device budget,
  spill tier armed).
- VACUOUS RUN: every leg must emit matches, and the eviction leg must
  genuinely churn the spill tier (rows_evicted > 0 AND
  rows_reloaded > 0) — a shape drift that stops spill from engaging
  would silently shrink what the gate covers.
- STEADY-STATE COMPILE: after the first device pass warmed the shared
  program cache, a FRESH engine replaying the same stream must compile
  ZERO XLA programs (the recompile-sentinel claim, scoped to the
  cep-advance / cep-prune program family).
- SERVING DIVERGENCE: matched-pattern lookups through the READ-REPLICA
  plane must agree with the live match-store probe on every key, and
  must return > 0 rows (vacuity guard on the queryable store).
- FRONTEND DIVERGENCE: the same lookups through the MULTI-PROCESS
  serving tier (shm hot cache + FrontendPool — GIL-free out-of-process
  match reads via ``CepMatchServingAdapter``) must decode to the
  identical row sets. Skipped LOUDLY when the native hotcache plane is
  unavailable (no toolchain): the tier cannot exist without it.

    JAX_PLATFORMS=cpu python tools/cep_smoke.py
    CEP_SMOKE_STEPS=... CEP_SMOKE_BATCH=... to scale.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

STEPS = int(os.environ.get("CEP_SMOKE_STEPS", 12))
BATCH = int(os.environ.get("CEP_SMOKE_BATCH", 256))
DENSE_KEYS = 40       # dense: per-key sequences actually complete
CHURN_KEYS = 20_000   # sparse: live partials >> device budget
BUDGET = 256          # slots/shard — the engine's floor, far below
                      # the churn leg's live set


def _steps(seed, n_keys):
    """(keys, vals, ts, watermark) tuples — event time advances with a
    trailing watermark so every fire drains that step's pending set."""
    rng = np.random.default_rng(seed)
    ts = 0
    out = []
    for _ in range(STEPS):
        keys = rng.integers(0, n_keys, size=BATCH).astype(np.int64)
        vals = rng.integers(0, 9, size=BATCH).astype(np.int64)
        tss = ts + np.sort(
            rng.integers(0, 30, size=BATCH)).astype(np.int64)
        ts += 25
        out.append((keys, vals, tss, ts - 5))
    return out


def drive(engine, steps):
    from flink_tpu.core.records import RecordBatch

    out = []
    for keys, vals, tss, wm in steps:
        b = RecordBatch.from_pydict(
            {"k": keys, "v": vals, "__key_id__": keys},
            timestamps=tss)
        out.extend(engine.process_batch(b))
        out.extend(engine.on_watermark(wm))
    return out


def rows_of(batches):
    """Flatten to (timestamp, sorted-row) tuples — order-preserving,
    so a reordered emission diverges even when the value set matches."""
    rows = []
    for b in batches:
        for r, t in zip(b.to_rows(),
                        np.asarray(b.timestamps).tolist()):
            rows.append((t, tuple(sorted(r.items()))))
    return rows


def main():
    import warnings

    warnings.filterwarnings("ignore")
    import time

    import jax

    from flink_tpu.cep.mesh_engine import MeshCepEngine
    from flink_tpu.cep.pattern import (
        AfterMatchSkipStrategy,
        Pattern,
    )
    from flink_tpu.observe import RecompileSentinel
    from flink_tpu.parallel.mesh import make_mesh

    P = min(len(jax.devices()), 8)
    mesh = make_mesh(P)
    errs = []
    t0 = time.perf_counter()

    def seq3(skip):
        return (Pattern.begin("a", skip=skip)
                .where(lambda b: np.asarray(b["v"]) % 3 == 0)
                .next("b")
                .where(lambda b: np.asarray(b["v"]) % 3 == 1)
                .next("c")
                .where(lambda b: np.asarray(b["v"]) % 3 == 2)
                .within(50))

    def mk(pat, backend, **kw):
        if backend == "device":
            return MeshCepEngine(pat, key_field="k", mesh=mesh,
                                 capacity_per_shard=BUDGET, **kw)
        return MeshCepEngine(pat, key_field="k", backend="host")

    # ---- bit-identity: 3-stage within, both skip strategies ----
    matches = 0
    for skip in (AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT,
                 AfterMatchSkipStrategy.NO_SKIP):
        pat = seq3(skip)
        steps = _steps(7, DENSE_KEYS)
        want = rows_of(drive(mk(pat, "host"), steps))
        got = rows_of(drive(mk(pat, "device"), steps))
        if want != got:
            errs.append(f"seq3/{skip.name}: device diverges from "
                        f"host oracle ({len(got)} vs {len(want)} "
                        "rows, or order/values differ)")
        if not want:
            errs.append(f"seq3/{skip.name}: zero matches — "
                        "vacuous run")
        matches += len(want)

    # ---- forced eviction: always-alive pattern, keys >> budget ----
    # the virtual start state keeps every seen key's column alive, so
    # residency grows without bound and the spill tier MUST churn
    churn = (Pattern.begin(
                 "a", skip=AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT)
             .next("b").where(lambda b: np.asarray(b["v"]) == 7))
    steps = _steps(11, CHURN_KEYS)
    want = rows_of(drive(mk(churn, "host"), steps))
    with tempfile.TemporaryDirectory() as td:
        dev = mk(churn, "device", spill_dir=td)
        got = rows_of(drive(dev, steps))
        sc = dev.spill_counters()
    if want != got:
        errs.append("churn: device diverges from host oracle under "
                    "paged eviction")
    if not want:
        errs.append("churn: zero matches — vacuous run")
    if sc.get("rows_evicted", 0) == 0:
        errs.append("churn: spill never engaged (rows_evicted=0) — "
                    "vacuous eviction coverage")
    if sc.get("rows_reloaded", 0) == 0:
        errs.append("churn: no evicted column ever reloaded "
                    "(rows_reloaded=0) — the restore-put path was "
                    "not covered")

    # ---- steady state: a fresh engine compiles NOTHING ----
    pat = seq3(AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT)
    steps = _steps(7, DENSE_KEYS)
    steady = mk(pat, "device")
    try:
        with RecompileSentinel(
                max_compiles=0, max_transfers=STEPS * 64,
                label="cep steady state") as s:
            drive(steady, steps)
        compiles = s.compiles
    except Exception as e:  # SteadyStateViolation included
        errs.append(f"steady-state: {e}")
        compiles = -1

    # ---- serving: replica-plane lookups == live match store ----
    serve = mk(pat, "device")
    adapter = serve.arm_match_replica()
    drive(serve, steps)
    qkeys = np.arange(DENSE_KEYS, dtype=np.int64)
    live = serve.query_match_batch(qkeys)
    rep, _gen = adapter.lookup_batch(qkeys)
    served = sum(len(r) for r in live)
    if served == 0:
        errs.append("serving: zero rows in the match store — "
                    "vacuous lookup leg")
    for i in range(DENSE_KEYS):
        if live[i] != rep[i]:
            errs.append(f"serving: replica row set for key {i} "
                        "diverges from the live probe")
            break

    # ---- frontend tier: shm frontends == live match store ----
    frontend_hits = _frontend_leg(mk, pat, steps, errs)

    result = {
        "cep_smoke": "ok" if not errs else "FAIL",
        "shards": P,
        "seq3_matches": matches,
        "churn_matches": len(want),
        "rows_evicted": sc.get("rows_evicted", 0),
        "rows_reloaded": sc.get("rows_reloaded", 0),
        "steady_state_compiles": compiles,
        "match_rows_served": served,
        "frontend_hits": frontend_hits,
        "seconds": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(result))
    for e in errs:
        print(f"FAIL: {e}", file=sys.stderr)
    return 1 if errs else 0


def _frontend_leg(mk, pat, steps, errs):
    """Matched-pattern lookups through the multi-process serving tier:
    owner primes the shm hot cache via CepMatchServingAdapter, frontend
    processes probe it over shared memory, and every decoded row set
    must match the live ``query_match_batch`` probe bit-for-bit. The
    second lookup round must hit the shm table (hits > 0): a packing
    regression would silently turn every probe into an owner crossing.
    Returns the frontend shm hit count (-1 = skipped)."""
    import queue

    from flink_tpu.cep.mesh_engine import CepMatchServingAdapter
    from flink_tpu.tenancy.frontend import FrontendPool
    from flink_tpu.tenancy.serving import ServingPlane

    with tempfile.TemporaryDirectory() as td:
        try:
            plane = ServingPlane(shm_dir=os.path.join(td, "hc"))
        except RuntimeError as e:
            print("SKIP: frontend serving leg NOT RUN — native "
                  f"hotcache plane unavailable ({e})", file=sys.stderr)
            return -1
        engine = mk(pat, "device")
        adapter = engine.arm_match_replica(serving=True)
        assert isinstance(adapter, CepMatchServingAdapter)
        plane.bind_job("cep", queue.Queue())
        plane.bind_replica("cep", "matches", adapter)
        drive(engine, steps)
        qkeys = np.arange(DENSE_KEYS, dtype=np.int64)
        live = engine.query_match_batch(qkeys)
        try:
            with FrontendPool(plane, n_frontends=2) as pool:
                pool.wait_ready()
                # round 1 fills the shm table through the miss path;
                # round 2 must serve out-of-process from shared memory
                pool.lookup_batch("cep", "matches", qkeys.tolist())
                got = pool.lookup_batch("cep", "matches",
                                        qkeys.tolist())
                stats = pool.fe_stats()
            hits = int(sum(r.get("probes_hit", r.get("hits", 0))
                           for r in stats))
            for i in range(DENSE_KEYS):
                if CepMatchServingAdapter.match_rows(got[i]) != live[i]:
                    errs.append(
                        f"frontend: decoded row set for key {i} "
                        "diverges from the live probe")
                    break
            if hits == 0:
                errs.append(
                    "frontend: zero shm hits — every lookup crossed "
                    "to the owner (match results stopped packing)")
            return hits
        finally:
            plane.shutdown_workers()


if __name__ == "__main__":
    sys.exit(main())
