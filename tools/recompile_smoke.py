"""Recompile-sentinel smoke: ZERO steady-state XLA recompiles for both
mesh engines at the bench shape (tier-1 gate).

Methodology: one warmup rep per engine compiles every step program
(scatter / merge / fire / reset / gather / put at their sticky-bucket
padded shapes), then each measured rep builds a FRESH engine over the
same mesh and replays the same stream shape (timestamps shifted so
event time advances and sessions/windows genuinely fire). Fresh engines
make the assertion strict: a step cache keyed on anything unstable
(engine identity, per-instance lambda, device object vs id) recompiles
on rep 2 and fails here. The sentinel also enforces a device->host
transfer budget — an unbatched per-leaf host read multiplies the
transfer count and trips it.

Spill is ON (max_device_slots below the live set) so the eviction /
page-reload / hybrid-fire kernels are part of the steady state too,
exactly like the mesh bench rows.

    JAX_PLATFORMS=cpu python tools/recompile_smoke.py
    RECOMPILE_SMOKE_RECORDS=... RECOMPILE_SMOKE_REPS=... to scale.

Exits non-zero on any steady-state compile, on a blown transfer
budget, or on zero fired windows (a vacuous run must not pass).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

GAP_MS = 16_000
WINDOW_MS = 5_000
NUM_KEYS = 50_000
BATCH = 8_192
#: records per ms of event time — slow event time is what keeps the
#: concurrent live set (keys per open window / sessions inside the gap)
#: ABOVE the per-shard device budget, so the evict/reload kernels run
RECORDS_PER_MS = 4


def _batches(total, rep, rng_seed=7):
    """The rep's record stream: identical SHAPE every rep (same batch
    sizes, same key multiset), event time shifted per rep so watermarks
    advance and windows/sessions close instead of being dropped late."""
    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )

    span = total // RECORDS_PER_MS  # ms of event time per rep
    # shift each rep by WHOLE windows: a non-aligned offset would slide
    # the tumbling-window phase, change how many windows close per
    # watermark, and walk the sticky fire buckets through new shapes
    stride = span + 10 * GAP_MS
    stride += -stride % WINDOW_MS
    offset = rep * stride
    rng = np.random.default_rng(rng_seed)  # same seed: same shapes
    produced = 0
    while produced < total:
        b = min(BATCH, total - produced)
        keys = rng.integers(0, NUM_KEYS, b).astype(np.int64)
        ts = offset + (produced
                       + np.arange(b, dtype=np.int64)) // RECORDS_PER_MS
        yield RecordBatch({
            KEY_ID_FIELD: keys,
            "v": np.ones(b, dtype=np.float32),
            TIMESTAMP_FIELD: ts,
        }), int(ts[-1])
        produced += b


def _drive(engine, total, rep):
    fired = 0
    last = 0
    for rb, last in _batches(total, rep):
        engine.process_batch(rb)
        fired += sum(len(b) for b in engine.on_watermark(last - GAP_MS))
    fired += sum(len(b) for b in engine.on_watermark(last + 100 * GAP_MS))
    return fired


def _make_sessions(mesh, budget):
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate

    return MeshSessionEngine(GAP_MS, SumAggregate("v"), mesh,
                             capacity_per_shard=budget,
                             max_device_slots=budget)


def _make_windows(mesh, budget):
    from flink_tpu.parallel.sharded_windower import MeshWindowEngine
    from flink_tpu.windowing.aggregates import SumAggregate
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    return MeshWindowEngine(TumblingEventTimeWindows.of(WINDOW_MS),
                            SumAggregate("v"), mesh,
                            capacity_per_shard=budget,
                            max_device_slots=budget)


def check_engine(name, make, mesh, total, reps, budget):
    from flink_tpu.observe import RecompileSentinel

    # warmup: compiles the whole step-program family at the padded
    # shapes the measured reps will reuse
    warm_fired = _drive(make(mesh, budget), total, rep=0)
    ok = True
    for rep in range(1, reps + 1):
        # FRESH engine per rep: the step caches must hit across engine
        # rebuilds (restarts, rescales), not just across batches.
        # Transfer budget: each watermark advance harvests one batched
        # result read, evictions/reloads add a bounded few more.
        engine = make(mesh, budget)
        with RecompileSentinel(
                max_compiles=0,
                max_transfers=max((total // BATCH) * 8, 64),
                label=f"{name} rep {rep}") as s:
            fired = _drive(engine, total, rep)
        evicted = int(engine.spill_counters().get("rows_evicted", 0))
        print(f"  {name} rep {rep}: fired={fired} compiles={s.compiles} "
              f"transfers={s.transfers} rows_evicted={evicted}")
        if fired == 0:
            print(f"FAIL: {name}: zero windows fired — vacuous run")
            ok = False
        if evicted == 0:
            # the gate's claim is that evict/reload/hybrid-fire kernels
            # are part of the guarded steady state — a shape change that
            # stops spill from engaging would silently shrink coverage
            print(f"FAIL: {name}: spill never engaged — the "
                  "evict/reload kernels were not covered")
            ok = False
    if warm_fired == 0:
        print(f"FAIL: {name}: zero windows fired in warmup")
        ok = False
    return ok


def _drive_interleaved(engines, total, rep, serve_keys):
    """Multiplex the same stream shape across N 'jobs' (one engine
    each), the session cluster's interleave collapsed to its essence,
    with a batched queryable-state lookup per engine per batch — the
    serving path is part of the guarded steady state too."""
    import numpy as np

    fired = 0
    last = 0
    for rb, last in _batches(total, rep):
        for eng in engines:
            eng.process_batch(rb)
            fired += sum(len(b) for b in eng.on_watermark(last - GAP_MS))
            eng.query_batch(np.asarray(serve_keys, dtype=np.int64))
    for eng in engines:
        fired += sum(len(b)
                     for b in eng.on_watermark(last + 100 * GAP_MS))
    return fired


#: batch sizes for the device-shuffle tier walk: per-shard chunk tiers
#: pad_bucket_size(ceil(b / 8)) cover {256, 512, 1024} twice over, so a
#: fused exchange program keyed on anything finer than the tier (raw
#: batch length, bucket width off the tier lattice) compiles mid-rep
#: and fails the sentinel
TIER_WALK_WARM = (8192, 4096, 2048, 6000, 3000, 1900)
TIER_WALK_RUN = (8000, 3500, 2200, 7000, 2600, 1800)


def _drive_sized(engine, sizes, offset, rng_seed=11):
    """Drive ``engine`` with one batch per entry of ``sizes`` (event
    time advancing so sessions genuinely fire), then flush."""
    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )

    rng = np.random.default_rng(rng_seed)
    fired = 0
    t = offset
    for b in sizes:
        keys = rng.integers(0, NUM_KEYS, b).astype(np.int64)
        ts = t + np.arange(b, dtype=np.int64) // RECORDS_PER_MS
        engine.process_batch(RecordBatch({
            KEY_ID_FIELD: keys,
            "v": np.ones(b, dtype=np.float32),
            TIMESTAMP_FIELD: ts,
        }))
        t = int(ts[-1]) + 1
        fired += sum(len(x)
                     for x in engine.on_watermark(t - GAP_MS))
    fired += sum(len(x)
                 for x in engine.on_watermark(t + 100 * GAP_MS))
    return fired


def check_device_shuffle_tiers(mesh, budget):
    """Device-shuffle phase: after one warmup engine walks every
    pad_bucket_size tier (both size lists), a FRESH engine replaying
    SHIFTED batch sizes — different lengths, same tier lattice — must
    compile NOTHING. This is exactly the recompile surface the fused
    exchange adds: its program shapes are (chunk tier, bucket-width
    tier), so a shape leak past the tiers shows up here as a
    steady-state compile."""
    from flink_tpu.observe import RecompileSentinel

    warm_eng = _make_sessions(mesh, budget)
    assert warm_eng.shuffle_mode == "device"
    warm_fired = _drive_sized(warm_eng, TIER_WALK_WARM, offset=0)
    warm_fired += _drive_sized(warm_eng, TIER_WALK_RUN,
                               offset=1 << 22)
    ok = True
    engine = _make_sessions(mesh, budget)
    with RecompileSentinel(
            max_compiles=0,
            max_transfers=max(len(TIER_WALK_RUN) * 8, 64),
            label="device-shuffle tier walk") as s:
        fired = _drive_sized(engine, TIER_WALK_RUN, offset=1 << 23)
    evicted = int(engine.spill_counters().get("rows_evicted", 0))
    print(f"  device-shuffle tiers: fired={fired} "
          f"compiles={s.compiles} transfers={s.transfers} "
          f"rows_evicted={evicted}")
    if fired == 0 or warm_fired == 0:
        print("FAIL: device-shuffle tiers: zero fires — vacuous run")
        ok = False
    return ok


def check_pallas_backend_phase(mesh, budget):
    """Stateplane backend-swap phase: the same tier walk under
    ``backend_scope("exchange-rank", "pallas")``. The pallas builders
    tag their PROGRAM_CACHE keys with the backend, so the swap pays its
    own warmup ONCE — after a warm engine walks the tier lattice in
    pallas scope, a FRESH engine replaying SHIFTED sizes (still in
    scope) must compile NOTHING. A backend hook that leaked into the
    key unstably (per-engine closure, config object identity) or that
    failed to key at all (silent retrace on every scope flip) shows up
    here as a steady-state compile. Skips LOUDLY when the pallas kernel
    is unavailable on this host."""
    from flink_tpu.observe import RecompileSentinel
    from flink_tpu.stateplane import backend_scope, pallas_available

    if not pallas_available():
        print("  pallas-backend tiers: SKIPPED — pallas kernel "
              "unavailable on this host; the backend-swap "
              "zero-recompile claim is NOT verified here")
        return True
    with backend_scope("exchange-rank", "pallas"):
        warm_eng = _make_sessions(mesh, budget)
        warm_fired = _drive_sized(warm_eng, TIER_WALK_WARM, offset=0)
        warm_fired += _drive_sized(warm_eng, TIER_WALK_RUN,
                                   offset=1 << 22)
        ok = True
        engine = _make_sessions(mesh, budget)
        with RecompileSentinel(
                max_compiles=0,
                max_transfers=max(len(TIER_WALK_RUN) * 8, 64),
                label="pallas-backend tier walk") as s:
            fired = _drive_sized(engine, TIER_WALK_RUN, offset=1 << 23)
    print(f"  pallas-backend tiers: fired={fired} "
          f"compiles={s.compiles} transfers={s.transfers}")
    if fired == 0 or warm_fired == 0:
        print("FAIL: pallas-backend tiers: zero fires — vacuous run")
        ok = False
    return ok


def check_two_level_exchange_tiers(mesh, budget):
    """Two-level (pod) exchange phase: a virtual (2, P/2) topology arms
    parallel/exchange2.py's stage-1/stage-2 program pair. After one
    warmup engine walks the tier lattice (both size lists), a FRESH
    engine on SHIFTED sizes must compile NOTHING — the pod programs'
    shapes are (chunk, W1, W2) tiers, and a leak past any level shows
    up here as a steady-state compile. Covers fresh-engine rebuilds:
    the PROGRAM_CACHE family must be hit, not rebuilt."""
    from flink_tpu.observe import RecompileSentinel
    from flink_tpu.parallel.mesh import HostTopology
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate

    P = int(mesh.devices.size)
    if P % 2:
        print("  two-level tiers: skipped (odd mesh)")
        return True
    topo = HostTopology(2, P // 2)

    def make():
        return MeshSessionEngine(GAP_MS, SumAggregate("v"), mesh,
                                 capacity_per_shard=budget,
                                 max_device_slots=budget,
                                 host_topology=topo)

    warm_eng = make()
    assert warm_eng._two_level_active()
    warm_fired = _drive_sized(warm_eng, TIER_WALK_WARM, offset=0)
    warm_fired += _drive_sized(warm_eng, TIER_WALK_RUN,
                               offset=1 << 22)
    ok = True
    engine = make()
    with RecompileSentinel(
            max_compiles=0,
            max_transfers=max(len(TIER_WALK_RUN) * 8, 64),
            label="two-level exchange tier walk") as s:
        fired = _drive_sized(engine, TIER_WALK_RUN, offset=1 << 23)
    traffic = engine.exchange2_traffic()
    print(f"  two-level tiers: fired={fired} "
          f"compiles={s.compiles} transfers={s.transfers} "
          f"cross_host_rows={traffic['rows_cross_host']}")
    if fired == 0 or warm_fired == 0:
        print("FAIL: two-level tiers: zero fires — vacuous run")
        ok = False
    if traffic["rows_cross_host"] == 0:
        print("FAIL: two-level tiers: no cross-host rows — the DCN "
              "stage never carried anything")
        ok = False
    return ok


#: join-phase batch-size walks: same tier lattice, shifted lengths —
#: a probe/ingest/eviction program keyed on anything finer than the
#: (chunk, probe-bucket, band, mirror) tiers compiles mid-rep here
JOIN_WALK_WARM = (4096, 2048, 1024, 3000, 1500, 900)
JOIN_WALK_RUN = (4000, 2200, 1100, 2800, 1300, 1000)


def _drive_join_sized(engine, sizes, offset, rng_seed=17):
    """Two-sided interval-join stream: one left + one right batch per
    entry of ``sizes``, event time advancing with a lagging watermark
    so the band stays populated AND the spill tier genuinely engages
    (keys >> budget)."""
    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )

    rng = np.random.default_rng(rng_seed)
    matches = 0
    t = offset
    for b in sizes:
        for side, name in ((0, "v"), (1, "w")):
            keys = rng.integers(0, NUM_KEYS, b).astype(np.int64)
            ts = t + np.arange(b, dtype=np.int64) // RECORDS_PER_MS
            out = engine.process_batch(RecordBatch({
                KEY_ID_FIELD: keys,
                name: np.ones(b, dtype=np.float32),
                TIMESTAMP_FIELD: ts,
            }), side)
            matches += sum(len(x) for x in out)
        t = int(ts[-1]) + 1
        engine.on_watermark(t - 3000)
    return matches


def _make_join(mesh, budget):
    from flink_tpu.joins import MeshIntervalJoinEngine

    # band as deep as the pruning horizon: probes reach well past the
    # resident (newest) rows into the paged tier, so cold service is
    # part of the guarded steady state (the vacuity check below)
    return MeshIntervalJoinEngine(
        -2500, 2500, mesh=mesh, capacity_per_shard=max(budget // 4,
                                                       256),
        max_device_slots=max(budget // 4, 256))


def check_join_phase(mesh, budget):
    """Join phase: after one warmup engine walks every tier of the
    banded-probe / ingest-exchange / eviction-gather program family
    (both batch-size lists), a FRESH interval-join engine replaying
    SHIFTED batch sizes — different lengths, same tier lattice — must
    compile NOTHING. Spill is armed and ASSERTED (rows must evict and
    cold candidates must serve from pages), so the eviction and
    cold-probe paths are part of the guarded steady state."""
    from flink_tpu.observe import RecompileSentinel

    warm = _make_join(mesh, budget)
    warm_matches = _drive_join_sized(warm, JOIN_WALK_WARM, offset=0)
    warm_matches += _drive_join_sized(warm, JOIN_WALK_RUN,
                                      offset=1 << 22)
    ok = True
    engine = _make_join(mesh, budget)
    with RecompileSentinel(
            max_compiles=0,
            max_transfers=max(len(JOIN_WALK_RUN) * 16, 64),
            label="join tier walk") as s:
        matches = _drive_join_sized(engine, JOIN_WALK_RUN,
                                    offset=1 << 23)
    sc = engine.spill_counters()
    print(f"  join tiers: matches={matches} compiles={s.compiles} "
          f"transfers={s.transfers} "
          f"rows_evicted={sc['rows_evicted']} "
          f"cold_served={sc['cold_rows_served']}")
    if matches == 0 or warm_matches == 0:
        print("FAIL: join tiers: zero matches — vacuous run")
        ok = False
    if sc["rows_evicted"] == 0 or sc["cold_rows_served"] == 0:
        print("FAIL: join tiers: spill never engaged — the eviction/"
              "cold-probe kernels were not covered")
        ok = False
    return ok


#: cep-phase batch-size walks: shifted lengths, same padded-lane tier
#: lattice — an advance/harvest/prune program keyed on raw batch
#: length (instead of the sticky padded tiers) compiles mid-walk here
CEP_WALK_WARM = (512, 256, 128, 384, 192, 96)
CEP_WALK_RUN = (448, 288, 144, 336, 224, 112)


def _drive_cep_sized(engine, sizes, offset, n_keys, rng):
    """One keyed batch + one trailing-watermark fire per entry of
    ``sizes`` — every fire drains that step's pending set, so the
    advance program runs at each shifted length."""
    from flink_tpu.core.records import RecordBatch

    matches = 0
    t = offset
    for n in sizes:
        keys = rng.integers(0, n_keys, n).astype(np.int64)
        vals = rng.integers(0, 9, n).astype(np.int64)
        ts = t + np.sort(
            rng.integers(0, 30, size=n)).astype(np.int64)
        t += 25
        engine.process_batch(RecordBatch.from_pydict(
            {"k": keys, "v": vals, "__key_id__": keys},
            timestamps=ts))
        out = engine.on_watermark(t - 5)
        matches += sum(len(b) for b in out)
    return matches, t


def check_cep_phase(mesh):
    """CEP phase: after warmup engines walk the padded-lane tier
    lattice for BOTH device program families — the within-window
    sequence (advance + within-prune) and the always-alive churn
    pattern (advance + evict/restore, spill armed, keys >> budget) —
    FRESH engines replaying SHIFTED batch sizes must compile NOTHING.
    Matches and spill churn are ASSERTED so neither leg can go
    vacuous."""
    import tempfile

    from flink_tpu.cep.mesh_engine import MeshCepEngine
    from flink_tpu.cep.pattern import (
        AfterMatchSkipStrategy,
        Pattern,
    )
    from flink_tpu.observe import RecompileSentinel

    skip = AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT
    within_pat = (Pattern.begin("a", skip=skip)
                  .where(lambda b: np.asarray(b["v"]) % 3 == 0)
                  .next("b")
                  .where(lambda b: np.asarray(b["v"]) % 3 == 1)
                  .within(50))
    churn_pat = (Pattern.begin("a", skip=skip)
                 .next("b")
                 .where(lambda b: np.asarray(b["v"]) == 7))

    def mk(pat, spill_dir=None):
        return MeshCepEngine(pat, key_field="k", mesh=mesh,
                             capacity_per_shard=256,
                             spill_dir=spill_dir)

    # warmup: both walks, both program families
    rng = np.random.default_rng(19)
    w_within = mk(within_pat)
    warm_m, t = _drive_cep_sized(w_within, CEP_WALK_WARM, 0, 64, rng)
    warm_m += _drive_cep_sized(w_within, CEP_WALK_RUN, t, 64, rng)[0]
    with tempfile.TemporaryDirectory() as td:
        w_churn = mk(churn_pat, spill_dir=td)
        _, t = _drive_cep_sized(w_churn, CEP_WALK_WARM, 0, 20_000,
                                rng)
        _drive_cep_sized(w_churn, CEP_WALK_RUN, t, 20_000, rng)

        ok = True
        within = mk(within_pat)
        churn = mk(churn_pat, spill_dir=td)
        with RecompileSentinel(
                max_compiles=0,
                max_transfers=len(CEP_WALK_RUN) * 6 * 64,
                label="cep tier walk") as s:
            m, t = _drive_cep_sized(within, CEP_WALK_RUN, 0, 64, rng)
            # two passes on the churn engine: the live key set must
            # outgrow the 8x256 slot budget so evict/restore programs
            # are part of the guarded steady state
            _, t2 = _drive_cep_sized(churn, CEP_WALK_RUN, 0, 20_000,
                                     rng)
            cm = _drive_cep_sized(churn, CEP_WALK_RUN, t2, 20_000,
                                  rng)[0]
        sc = churn.spill_counters()
    print(f"  cep tiers: matches={m} churn_matches={cm} "
          f"compiles={s.compiles} transfers={s.transfers} "
          f"rows_evicted={sc['rows_evicted']}")
    if m == 0 or warm_m == 0:
        print("FAIL: cep tiers: zero matches — vacuous run")
        ok = False
    if cm == 0:
        print("FAIL: cep tiers: churn leg emitted nothing — "
              "vacuous run")
        ok = False
    if sc["rows_evicted"] == 0:
        print("FAIL: cep tiers: spill never engaged — the "
              "evict/restore programs were not covered")
        ok = False
    return ok


def check_second_job_on_warm_cluster(mesh, total, budget):
    """The tenancy contract: after job A warms the cluster (ingest,
    fire, evict AND serving programs), a SECOND job's fresh engines on
    the same mesh — interleaved with a third, plus concurrent batched
    lookups — compile NOTHING."""
    from flink_tpu.observe import RecompileSentinel
    from flink_tpu.tenancy.program_cache import PROGRAM_CACHE

    serve_keys = list(range(0, NUM_KEYS, NUM_KEYS // 16))
    with PROGRAM_CACHE.job_scope("smoke-warm"):
        warm_fired = _drive_interleaved(
            [_make_sessions(mesh, budget)], total, rep=0,
            serve_keys=serve_keys)
    PROGRAM_CACHE.reset_stats()
    ok = True
    with PROGRAM_CACHE.job_scope("smoke-job2"):
        with RecompileSentinel(
                max_compiles=0,
                max_transfers=max((total // BATCH) * 24, 64),
                label="2 jobs on warm cluster") as s:
            fired = _drive_interleaved(
                [_make_sessions(mesh, budget),
                 _make_sessions(mesh, budget)],
                total, rep=1, serve_keys=serve_keys)
    misses = PROGRAM_CACHE.stats_for("smoke-job2")["misses"]
    print(f"  multi-tenant: fired={fired} compiles={s.compiles} "
          f"transfers={s.transfers} cache_misses={misses}")
    if fired == 0 or warm_fired == 0:
        print("FAIL: multi-tenant: zero windows fired — vacuous run")
        ok = False
    if misses:
        print(f"FAIL: multi-tenant: second job paid {misses} program-"
              "cache misses on a warm cluster")
        ok = False
    return ok


def main():
    import warnings

    warnings.filterwarnings("ignore")
    import jax

    from flink_tpu.observe.recompile_sentinel import compile_count
    from flink_tpu.parallel.mesh import make_mesh

    total = int(os.environ.get("RECOMPILE_SMOKE_RECORDS", 1 << 16))
    reps = max(int(os.environ.get("RECOMPILE_SMOKE_REPS", 2)), 1)
    P = min(len(jax.devices()), 8)
    mesh = make_mesh(P)
    # budgets well BELOW the concurrent live set per shard (thousands
    # of keys per open window x ~4 live slices, sessions alive inside
    # the 16 s gap) so the evict/reload/hybrid-fire kernels genuinely
    # run — check_engine FAILS if rows_evicted stays 0 (vacuous-coverage
    # guard). The window engine's floor is one slice's per-shard key set
    # (~2.1k here): a batch's touched namespaces are eviction-protected,
    # so a budget under that is an irreducible SlotTableFullError.
    budgets = {"mesh-sessions": 2048, "mesh-windows": 4096}
    ok = True
    for name, make in (("mesh-sessions", _make_sessions),
                       ("mesh-windows", _make_windows)):
        try:
            ok = check_engine(name, make, mesh, total, reps,
                              budgets[name]) and ok
        except Exception as e:  # SteadyStateViolation included
            print(f"FAIL: {name}: {e}")
            ok = False
    try:
        ok = check_device_shuffle_tiers(
            mesh, budgets["mesh-sessions"]) and ok
    except Exception as e:  # SteadyStateViolation included
        print(f"FAIL: device-shuffle tiers: {e}")
        ok = False
    try:
        ok = check_pallas_backend_phase(
            mesh, budgets["mesh-sessions"]) and ok
    except Exception as e:  # SteadyStateViolation included
        print(f"FAIL: pallas-backend tiers: {e}")
        ok = False
    try:
        ok = check_two_level_exchange_tiers(
            mesh, budgets["mesh-sessions"]) and ok
    except Exception as e:  # SteadyStateViolation included
        print(f"FAIL: two-level tiers: {e}")
        ok = False
    try:
        ok = check_join_phase(mesh, budgets["mesh-sessions"]) and ok
    except Exception as e:  # SteadyStateViolation included
        print(f"FAIL: join tiers: {e}")
        ok = False
    try:
        ok = check_cep_phase(mesh) and ok
    except Exception as e:  # SteadyStateViolation included
        print(f"FAIL: cep tiers: {e}")
        ok = False
    try:
        ok = check_second_job_on_warm_cluster(
            mesh, total, budgets["mesh-sessions"]) and ok
    except Exception as e:  # SteadyStateViolation included
        print(f"FAIL: multi-tenant: {e}")
        ok = False
    print(f"recompile smoke: shards={P} records={total} reps={reps} "
          f"process_compiles={compile_count()} "
          f"=> {'OK' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
