"""TPU backend diagnosis harness — pin the failure layer, don't wait.

Round-4 verdict: four rounds of probes recorded only "backend init hang";
nothing committed localized *where* init is stuck. This tool runs a probe
matrix and writes a machine-readable report under ``tpu_results/``:

1. **Relay TCP reachability.** The axon PJRT plugin (the only path to the
   chip in this environment: ``JAX_PLATFORMS=axon``,
   ``PALLAS_AXON_POOL_IPS=127.0.0.1``) routes ``jax.devices()`` through a
   loopback relay — per the plugin's own registration code
   (``axon/register/pjrt.py``: "All defer the :8082 session to first
   stateful RPC; jax.devices() goes via :8083 stateless"). We TCP-connect
   to both ports (plus the orchestrator HTTP port if named in env) and
   record connect/refused/timeout per port, plus a full listening-socket
   snapshot (``ss -tln``).
2. **Probe matrix.** Each cell = a subprocess that imports jax, calls
   ``jax.devices()``, and runs one tiny jitted add:
     - ``axon``  : environment as-is (sitecustomize registers the plugin).
     - ``libtpu``: ``JAX_PLATFORMS=tpu`` with the axon sitecustomize off
       ``PYTHONPATH`` — distinguishes "no local chip" (fails fast) from
       "relay dead" (axon hangs).
     - ``cpu``   : sanity control.
3. **Stack at timeout.** Each probe subprocess arms
   ``faulthandler.dump_traceback_later(timeout)`` so a hang records the
   exact Python frame (and whether it is blocked inside a native PJRT
   call) instead of just "hang".

Usage: ``python tools/tpu_diagnose.py [--timeout 60] [--out tpu_results]``

Exit code 0 always (diagnosis, not a gate); the JSON carries the verdict.
Reference analog: Flink's network stack self-diagnostics live in its
connection-manager logging (``flink-runtime/.../io/network/netty/``); this
fills the same "which layer is down" role for the device link.
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROBE_SRC = r"""
import faulthandler, os, sys, time
faulthandler.dump_traceback_later({timeout}, exit=True)
t0 = time.monotonic()
import jax
print("IMPORT_OK %.2fs" % (time.monotonic() - t0), flush=True)
if {resync}:
    # the axon sitecustomize sets jax_platforms="axon,cpu" via
    # jax.config at interpreter start, silently overriding the
    # JAX_PLATFORMS env var — re-assert it (what the repo's
    # flink_tpu.platform.sync_platform() does)
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)
t0 = time.monotonic()
devs = jax.devices()
print("DEVICES_OK %.2fs %s" % (time.monotonic() - t0, devs), flush=True)
t0 = time.monotonic()
import jax.numpy as jnp
out = jax.jit(lambda x: x + 1)(jnp.arange(8))
out.block_until_ready()
print("JIT_OK %.2fs %s" % (time.monotonic() - t0, list(out)), flush=True)
faulthandler.cancel_dump_traceback_later()
"""


def tcp_check(host: str, port: int, timeout: float = 3.0) -> dict:
    t0 = time.monotonic()
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return {"port": port, "result": "connected",
                    "ms": round((time.monotonic() - t0) * 1e3, 1)}
    except ConnectionRefusedError:
        return {"port": port, "result": "refused",
                "ms": round((time.monotonic() - t0) * 1e3, 1)}
    except (socket.timeout, TimeoutError):
        return {"port": port, "result": "timeout", "ms": round(timeout * 1e3)}
    except OSError as e:
        return {"port": port, "result": f"oserror: {e}", "ms": None}


def run_probe(name: str, env_overrides: dict, timeout: float,
              resync: bool = False) -> dict:
    env = dict(os.environ)
    env.update({k: v for k, v in env_overrides.items() if v is not None})
    for k, v in env_overrides.items():
        if v is None:
            env.pop(k, None)
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             PROBE_SRC.format(timeout=timeout, resync=resync)],
            capture_output=True, text=True, timeout=timeout + 30, env=env,
        )
        out, err, rc = proc.stdout, proc.stderr, proc.returncode
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        rc = -1
    wall = time.monotonic() - t0
    stages = [ln for ln in out.splitlines()
              if ln.startswith(("IMPORT_OK", "DEVICES_OK", "JIT_OK"))]
    reached = stages[-1].split()[0] if stages else "NOTHING"
    ok = reached == "JIT_OK" and rc == 0
    # keep the tail of stderr — it has the faulthandler stack on hang
    err_tail = "\n".join(err.splitlines()[-40:])
    return {"probe": name, "ok": ok, "rc": rc, "wall_s": round(wall, 2),
            "reached": reached, "stages": stages, "stderr_tail": err_tail,
            "env": {k: env_overrides[k] for k in env_overrides}}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=float, default=60.0)
    ap.add_argument("--out", default=os.path.join(REPO, "tpu_results"))
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)

    report: dict = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                    "timeout_s": args.timeout}

    # --- layer 0: env snapshot -------------------------------------------
    report["env"] = {k: v for k, v in os.environ.items()
                     if any(s in k.upper() for s in
                            ("AXON", "TPU", "JAX", "XLA", "PALLAS"))}

    # --- layer 1: relay TCP reachability ---------------------------------
    relay_ip = os.environ.get("PALLAS_AXON_POOL_IPS", "127.0.0.1").split(",")[0]
    # 8082/8083: session + stateless ports named in the plugin's own
    # registration comments; 8080/443: orchestrator guesses.
    ports = [8082, 8083, 8080, 443, 2024]
    report["relay_tcp"] = {"host": relay_ip,
                           "checks": [tcp_check(relay_ip, p) for p in ports]}
    try:
        ss = subprocess.run(["ss", "-tln"], capture_output=True, text=True,
                            timeout=10)
        report["listening_sockets"] = ss.stdout.splitlines()
    except Exception as e:  # pragma: no cover
        report["listening_sockets"] = [f"ss failed: {e}"]

    # --- layer 2: probe matrix -------------------------------------------
    # strip only the axon sitecustomize dir (basename match — a bare
    # "axon" substring would also drop e.g. /home/x/taxonomy-lib)
    axon_site = os.environ.get("PYTHONPATH", "")
    no_axon_path = ":".join(
        p for p in axon_site.split(":")
        if os.path.basename(p.rstrip("/")) != ".axon_site") or None
    matrix = [
        # resync=True: re-assert JAX_PLATFORMS after import, since the
        # axon sitecustomize overrides it via jax.config — this cell
        # doubles as proof that sync_platform() is a sufficient antidote
        ("cpu_synced", {"JAX_PLATFORMS": "cpu"}, True),
        ("libtpu_plain",
         {"JAX_PLATFORMS": "tpu", "PYTHONPATH": no_axon_path}, False),
        ("axon_plugin", {}, False),  # environment as-is
    ]
    report["probes"] = []
    for name, overrides, resync in matrix:
        print(f"# probing {name} (timeout {args.timeout}s)...", flush=True)
        res = run_probe(name, overrides, args.timeout, resync=resync)
        print(f"#   -> reached={res['reached']} ok={res['ok']} "
              f"wall={res['wall_s']}s", flush=True)
        report["probes"].append(res)

    # --- verdict ----------------------------------------------------------
    tcp = {c["port"]: c["result"] for c in report["relay_tcp"]["checks"]}
    axon = next(p for p in report["probes"] if p["probe"] == "axon_plugin")
    plain = next(p for p in report["probes"] if p["probe"] == "libtpu_plain")
    cpu = next(p for p in report["probes"] if p["probe"] == "cpu_synced")
    report["sync_platform_antidote_works"] = cpu["ok"]
    if axon["ok"]:
        verdict = "TPU REACHABLE via axon relay — capture benchmarks now"
    elif tcp.get(8082) != "connected" and tcp.get(8083) != "connected":
        verdict = ("relay DOWN: nothing accepting TCP on "
                   f"{relay_ip}:8082/:8083 (plugin's session/stateless "
                   "ports) — the hang is the plugin's connect/claim retry "
                   "loop, not XLA, not the chip. Plain libtpu: "
                   + (plain["stages"][-1] if plain["stages"] else
                      plain["stderr_tail"].splitlines()[-1]
                      if plain["stderr_tail"] else "no output"))
    else:
        verdict = ("relay port open but init still failed — see "
                   "axon_plugin.stderr_tail for the stack at timeout")
    report["verdict"] = verdict

    fname = os.path.join(args.out,
                         time.strftime("diagnose_%Y%m%d_%H%M%S.json",
                                       time.gmtime()))
    with open(fname, "w") as f:
        json.dump(report, f, indent=1)
    latest = os.path.join(args.out, "diagnose_latest.json")
    with open(latest, "w") as f:
        json.dump(report, f, indent=1)
    print(f"# report -> {fname}")
    print(json.dumps({"verdict": verdict,
                      "relay_tcp": tcp,
                      "axon_reached": axon["reached"],
                      "plain_libtpu_reached": plain["reached"]}))


if __name__ == "__main__":
    main()
