"""Chaos smoke: a seeded crash-restore-verify run for the tier-1 gate.

Drives the mesh session engine (paged spill, dispatch-ahead,
device-mode shuffle — the default) through a keyed-session stream with
periodic checkpoints while a fault plan injects THREE engine crashes
(a dispatch fence, a broken page reload, and the device data plane
dying mid-batch AFTER the fused exchange+scatter dispatch) and ONE torn
checkpoint write. The run FAILS (non-zero exit) if

- the committed output diverges from the fault-free single-device
  oracle by even one window (the exactly-once claim), or
- any planned fault was never injected (the plan went stale — a fault
  point moved or a schedule stopped being reachable), or
- the torn checkpoint was restored instead of skipped.

Everything is reproducible from the pinned (plan, seed): rerunning
this script reproduces the same crashes at the same hits. Runtime is a
few seconds on CPU (budgeted well under 60 s in tools/tier1.sh).

    JAX_PLATFORMS=cpu python tools/chaos_smoke.py
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# must precede the first jax import: on CPU the mesh needs virtual devices
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

GAP = 100
SEED = 7
NUM_KEYS = int(os.environ.get("CHAOS_SMOKE_KEYS", 6000))
N_STEPS = int(os.environ.get("CHAOS_SMOKE_STEPS", 8))
PER_STEP = int(os.environ.get("CHAOS_SMOKE_PER_STEP", 1500))
# shard-loss scenario shape: its OWN knobs so the bench suite can scale
# it up without disturbing the legacy scenario's pinned fault schedules
SL_KEYS = int(os.environ.get("CHAOS_SHARD_LOSS_KEYS", NUM_KEYS))
SL_STEPS = int(os.environ.get("CHAOS_SHARD_LOSS_STEPS", N_STEPS))
SL_PER_STEP = int(os.environ.get("CHAOS_SHARD_LOSS_PER_STEP", PER_STEP))
SL_SLOTS = int(os.environ.get("CHAOS_SHARD_LOSS_SLOTS", 1024))


def _steps(n_steps=None, per_step=None, num_keys=None):
    """~12k events by default, live session set far beyond the
    1024-slot/shard budget so page eviction + reload are genuinely on
    the path."""
    n_steps = N_STEPS if n_steps is None else n_steps
    per_step = PER_STEP if per_step is None else per_step
    num_keys = NUM_KEYS if num_keys is None else num_keys
    rng = np.random.default_rng(17)
    out = []
    for s in range(n_steps):
        keys = rng.integers(0, num_keys, per_step).astype(np.int64)
        vals = rng.random(per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        out.append((keys, vals, ts, (s - 1) * 80))
    return out


def shard_loss_scenario() -> int:
    """Kill 1 of 4 shards mid-stream (device.lost at a batch boundary,
    paged spill armed with forced eviction): the run FAILS unless the
    recovery was genuinely PARTIAL — only the dead shard's key groups
    restored from their checkpoint unit, and the replay volume bounded
    by ~1/shards of the stream (+padding). A partial recovery silently
    regressing to full replay trips the gate."""
    from flink_tpu.chaos.harness import (
        ChaosDivergenceError,
        run_shard_loss_verify,
    )
    from flink_tpu.chaos.injection import FaultPlan, FaultRule
    from flink_tpu.parallel.mesh import make_mesh
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate
    from flink_tpu.windowing.sessions import SessionWindower

    shards = 4
    mesh = make_mesh(shards)
    plan = FaultPlan(rules=[
        # mid-stream loss of shard 1: the 15th boundary probe of that
        # shard lands in step ~7's ingest (2 probes per step)
        FaultRule(pattern="device.lost", nth=15, where={"shard": 1}),
    ])

    def make_engine():
        return MeshSessionEngine(
            GAP, SumAggregate("v"), mesh,
            capacity_per_shard=max(1 << 14, SL_SLOTS),
            max_device_slots=SL_SLOTS, max_dispatch_ahead=2)

    def make_oracle():
        return SessionWindower(
            GAP, SumAggregate("v"),
            capacity=max(1 << 15, 2 * SL_KEYS))

    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="chaos-shard-loss-") as tmp:
        try:
            report = run_shard_loss_verify(
                make_engine, make_oracle,
                _steps(SL_STEPS, SL_PER_STEP, SL_KEYS), plan, seed=SEED,
                ckpt_root=os.path.join(tmp, "ckpt"), checkpoint_every=2)
        except ChaosDivergenceError as e:
            print(f"CHAOS SMOKE FAILED: shard-loss output diverged\n{e}",
                  file=sys.stderr)
            return 1
    row = {
        "bench": "chaos_smoke_shard_loss",
        "seconds": round(time.perf_counter() - t0, 2),
        "events": report.events,
        "shards": shards,
        **report.signature(),
        "shard_loss_recovery_ms": round(report.shard_loss_recovery_ms,
                                        1),
    }
    print(json.dumps(row))
    failures = []
    if report.shards_lost != 1:
        failures.append(
            f"expected exactly 1 shard loss, got {report.shards_lost}")
    if report.shard_restores != 1:
        failures.append(
            "the dead shard's key groups were never restored from "
            f"their checkpoint unit (shard_restores="
            f"{report.shard_restores})")
    if report.records_replayed <= 0:
        failures.append("no records were replayed — the loss happened "
                        "before any progress (stale schedule?)")
    # THE bounded-replay gate: a single-shard loss must replay about
    # 1/shards of the stream, never the whole backlog. The replay
    # window is at most checkpoint_every+1 steps of the range's share;
    # events/shards is ~2x that here — generous padding, but a
    # regression to full replay (~5x) trips it hard.
    budget = report.events // shards
    if report.records_replayed > budget:
        failures.append(
            f"replay volume {report.records_replayed} exceeds "
            f"events/shards = {budget} — partial recovery regressed "
            "toward full replay")
    if failures:
        print("CHAOS SMOKE FAILED: " + "; ".join(failures),
              file=sys.stderr)
        return 1
    return 0


def main() -> int:
    from flink_tpu.chaos.harness import (
        ChaosDivergenceError,
        run_crash_restore_verify,
    )
    from flink_tpu.chaos.injection import FaultPlan, FaultRule
    from flink_tpu.parallel.mesh import make_mesh
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate
    from flink_tpu.windowing.sessions import SessionWindower

    mesh = make_mesh(8)
    plan = FaultPlan(rules=[
        # crash 1: fence failure mid-dispatch-ahead (batches in flight)
        FaultRule(pattern="mesh.dispatch_fence", nth=5, kind="raise"),
        # crash 2: a page reload that stays broken past the retry budget
        FaultRule(pattern="spill.page_reload", nth=3, kind="raise"),
        # crash 3: the device data plane dies mid-batch, AFTER the
        # fused exchange+scatter was dispatched (shuffle.mode=device is
        # the engine default — the post-dispatch site is on every
        # batch's path)
        FaultRule(pattern="shuffle.device_exchange", nth=10,
                  kind="raise"),
        # the torn write: 2nd checkpoint's rename lands, its bytes don't
        FaultRule(pattern="checkpoint.write.torn", nth=2, kind="drop"),
    ])

    def make_engine():
        return MeshSessionEngine(
            GAP, SumAggregate("v"), mesh,
            capacity_per_shard=1 << 14, max_device_slots=1024,
            max_dispatch_ahead=2)

    def make_oracle():
        return SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)

    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="chaos-smoke-") as tmp:
        try:
            report = run_crash_restore_verify(
                make_engine, make_oracle, _steps(), plan, seed=SEED,
                ckpt_root=os.path.join(tmp, "ckpt"), checkpoint_every=2)
        except ChaosDivergenceError as e:
            print(f"CHAOS SMOKE FAILED: output diverged\n{e}",
                  file=sys.stderr)
            return 1
    row = {
        "bench": "chaos_smoke",
        "seconds": round(time.perf_counter() - t0, 2),
        "events": report.events,
        "windows": report.windows,
        **report.signature(),
        "corrupt_checkpoints_skipped": report.corrupt_checkpoints_skipped,
        "retries": report.retries,
        "recoveries": report.recoveries,
    }
    print(json.dumps(row))
    failures = []
    want_points = {"mesh.dispatch_fence", "spill.page_reload",
                   "shuffle.device_exchange", "checkpoint.write.torn"}
    missed = want_points - set(report.faults_injected)
    if missed:
        failures.append(f"planned faults never injected: {sorted(missed)}")
    if report.crashes != 3:
        failures.append(f"expected exactly 3 crashes, got {report.crashes}")
    if report.corrupt_checkpoints_skipped < 1:
        failures.append("the torn checkpoint was never detected/skipped")
    if failures:
        print("CHAOS SMOKE FAILED: " + "; ".join(failures),
              file=sys.stderr)
        return 1
    # partial failover: lose one shard, not the job (its own gate)
    return shard_loss_scenario()


if __name__ == "__main__":
    sys.exit(main())
