"""Join smoke (tier-1 gate): the device-native interval + temporal
join engines against the host-numpy oracle.

FAILS on:
- ORACLE DIVERGENCE: any emitted batch differing — bit-for-bit,
  including order — between the device engine (fused device-mode
  exchange) and the host-backend oracle, for the interval engine
  (under forced paged eviction) and the temporal engine (versioned
  plane + late-row drops).
- STEADY-STATE COMPILE: after the oracle pass warmed the shared
  program cache, a FRESH device engine replaying the same stream must
  compile ZERO XLA programs (the recompile-sentinel claim, scoped to
  the join program family).
- VACUOUS RUN: the spill tier must genuinely engage (rows evicted AND
  cold candidates served from pages) — a shape drift that stops spill
  from engaging would silently shrink what the gate covers.

    JAX_PLATFORMS=cpu python tools/join_smoke.py
    JOIN_SMOKE_STEPS=... JOIN_SMOKE_BATCH=... to scale.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

STEPS = int(os.environ.get("JOIN_SMOKE_STEPS", 8))
BATCH = int(os.environ.get("JOIN_SMOKE_BATCH", 2048))
KEYS = 40_000
BUDGET = 512          # slots/shard/side — far below the live set
BAND = 2500           # ms: deep enough to probe into the paged tier
WM_LAG = 3000


def _batch(rng, t, name):
    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )

    keys = rng.integers(0, KEYS, BATCH).astype(np.int64)
    ts = t + np.arange(BATCH, dtype=np.int64) // 4
    return RecordBatch({
        KEY_ID_FIELD: keys,
        name: rng.random(BATCH).astype(np.float32),
        TIMESTAMP_FIELD: ts,
    }), int(ts[-1]) + 1


def drive_interval(engine, seed=23):
    rng = np.random.default_rng(seed)
    out = []
    t = 0
    for _ in range(STEPS):
        for side, name in ((0, "v"), (1, "w")):
            b, t = _batch(rng, t, name)
            out += engine.process_batch(b, side)
        engine.on_watermark(t - WM_LAG)
    return out


def drive_temporal(engine, seed=29):
    rng = np.random.default_rng(seed)
    out = []
    t = 0
    for _ in range(STEPS):
        b, _ = _batch(rng, t, "rate")
        out += engine.process_batch(b, 1)
        b, t = _batch(rng, t, "v")
        out += engine.process_batch(b, 0)
        out += engine.on_watermark(t - WM_LAG)
    out += engine.on_watermark(1 << 40)
    return out


def diff_batches(got, want, label):
    if len(got) != len(want):
        return [f"{label}: {len(got)} batches vs oracle {len(want)}"]
    errs = []
    for i, (a, b) in enumerate(zip(got, want)):
        if sorted(a.names()) != sorted(b.names()):
            errs.append(f"{label}[{i}]: schema differs")
            continue
        if len(a) != len(b):
            errs.append(f"{label}[{i}]: {len(a)} rows vs {len(b)}")
            continue
        for n in a.names():
            if not np.array_equal(np.asarray(a[n]),
                                  np.asarray(b[n])):
                errs.append(f"{label}[{i}]: column {n} diverges")
                break
    return errs


def main():
    import warnings

    warnings.filterwarnings("ignore")
    import time

    import jax

    from flink_tpu.joins import (
        MeshIntervalJoinEngine,
        MeshTemporalJoinEngine,
    )
    from flink_tpu.observe import RecompileSentinel
    from flink_tpu.parallel.mesh import make_mesh

    P = min(len(jax.devices()), 8)
    mesh = make_mesh(P)
    errs = []

    def mk_interval(backend):
        kw = dict(capacity_per_shard=BUDGET, max_device_slots=BUDGET)
        if backend == "device":
            return MeshIntervalJoinEngine(-BAND, BAND, mesh=mesh,
                                          **kw)
        return MeshIntervalJoinEngine(-BAND, BAND, backend="host",
                                      num_shards=P, **kw)

    # ---- interval: device vs oracle, forced eviction ----
    t0 = time.perf_counter()
    dev = mk_interval("device")
    got = drive_interval(dev)
    want = drive_interval(mk_interval("host"))
    errs += diff_batches(got, want, "interval")
    matches = sum(len(b) for b in got)
    sc = dev.spill_counters()
    if matches == 0:
        errs.append("interval: zero matches — vacuous run")
    if sc["rows_evicted"] == 0:
        errs.append("interval: spill never engaged (rows_evicted=0)")
    if sc["cold_rows_served"] == 0:
        errs.append("interval: no cold candidate ever served from "
                    "the page tier — the band never reached spilled "
                    "rows (vacuous spill coverage)")

    # ---- temporal: device vs oracle ----
    tdev = MeshTemporalJoinEngine(mesh=mesh,
                                  capacity_per_shard=BUDGET,
                                  max_device_slots=BUDGET)
    tgot = drive_temporal(tdev)
    twant = drive_temporal(MeshTemporalJoinEngine(
        backend="host", num_shards=P, capacity_per_shard=BUDGET,
        max_device_slots=BUDGET))
    errs += diff_batches(tgot, twant, "temporal")
    tmatches = sum(len(b) for b in tgot)
    if tmatches == 0:
        errs.append("temporal: zero matches — vacuous run")

    # ---- steady state: a fresh engine compiles NOTHING ----
    steady = mk_interval("device")
    try:
        with RecompileSentinel(
                max_compiles=0, max_transfers=STEPS * 16,
                label="join steady state") as s:
            drive_interval(steady)
        compiles = s.compiles
    except Exception as e:  # SteadyStateViolation included
        errs.append(f"steady-state: {e}")
        compiles = -1

    result = {
        "join_smoke": "ok" if not errs else "FAIL",
        "shards": P,
        "interval_matches": matches,
        "temporal_matches": tmatches,
        "rows_evicted": sc["rows_evicted"],
        "cold_rows_served": sc["cold_rows_served"],
        "steady_state_compiles": compiles,
        "seconds": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(result))
    for e in errs:
        print(f"FAIL: {e}", file=sys.stderr)
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
