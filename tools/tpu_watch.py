"""Persistent TPU-backend watcher.

The tunneled TPU backend in this environment comes and goes (it answered in
round 1, hung in rounds 2-3). This watcher probes it on a loop; the moment a
probe succeeds it captures the full benchmark playbook on hardware — both
window layouts, a micro-batch sweep, and a cProfile — and writes everything
under ``tpu_results/``. Run it in the background for the whole round:

    python tools/tpu_watch.py >> /tmp/tpu_watch.log 2>&1 &

Exit conditions: after a successful capture it keeps probing (a later capture
overwrites with fresher numbers) unless TPU_WATCH_ONCE=1.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "tpu_results")
sys.path.insert(0, REPO)

from bench import probe_backend  # noqa: E402  (single probe implementation)


def probe(timeout_s=120):
    t0 = time.time()
    ok, info = probe_backend(timeouts=(timeout_s,))
    return ok and info in ("tpu", "axon"), info, time.time() - t0


def capture():
    os.makedirs(OUT, exist_ok=True)
    stamp = time.strftime("%Y%m%d_%H%M%S")
    env = dict(os.environ, BENCH_SKIP_PROBE="1")
    results = {"stamp": stamp, "runs": []}

    # 1. headline bench, both layouts (bench.py does this internally)
    try:
        p = subprocess.run([sys.executable, "bench.py"], cwd=REPO, env=env,
                           capture_output=True, text=True, timeout=2400)
        results["runs"].append({"name": "bench_default", "rc": p.returncode,
                                "stdout": p.stdout, "stderr": p.stderr[-8000:]})
    except subprocess.TimeoutExpired:
        results["runs"].append({"name": "bench_default", "error": "timeout"})

    # 2. micro-batch x dispatch-depth sweep (smaller record count per
    # point to bound time; depth is THE lever for the tunneled high-RTT
    # device link)
    for bs, da in ((1 << 20, 8), (1 << 19, 8), (1 << 21, 8),
                   (1 << 20, 16), (1 << 20, 4)):
        e = dict(env, BENCH_RECORDS=str(10_000_000),
                 BENCH_BATCH_SIZE=str(bs), BENCH_DISPATCH_AHEAD=str(da))
        try:
            p = subprocess.run([sys.executable, "bench.py"], cwd=REPO, env=e,
                               capture_output=True, text=True, timeout=1200)
            results["runs"].append({"name": f"sweep_bs{bs}_da{da}",
                                    "rc": p.returncode, "stdout": p.stdout,
                                    "stderr": p.stderr[-4000:]})
        except subprocess.TimeoutExpired:
            results["runs"].append({"name": f"sweep_bs{bs}_da{da}",
                                    "error": "timeout"})
        with open(os.path.join(OUT, f"capture_{stamp}.json"), "w") as f:
            json.dump(results, f, indent=1)

    # 3. profile
    try:
        p = subprocess.run(
            [sys.executable, "tools/profile_bench.py", "8000000"], cwd=REPO,
            env=env, capture_output=True, text=True, timeout=1800)
        with open(os.path.join(OUT, f"profile_{stamp}.txt"), "w") as f:
            f.write(p.stderr)
        results["runs"].append({"name": "profile", "rc": p.returncode})
    except subprocess.TimeoutExpired:
        results["runs"].append({"name": "profile", "error": "timeout"})

    with open(os.path.join(OUT, f"capture_{stamp}.json"), "w") as f:
        json.dump(results, f, indent=1)
    print(f"[tpu_watch] capture complete -> {OUT}/capture_{stamp}.json",
          flush=True)


def main():
    interval = int(os.environ.get("TPU_WATCH_INTERVAL_S", "300"))
    while True:
        ok, info, dt = probe()
        print(f"[tpu_watch] {time.strftime('%H:%M:%S')} probe: ok={ok} "
              f"info={info} dt={dt:.1f}s", flush=True)
        if ok:
            with open("/tmp/TPU_UP", "w") as f:
                f.write(time.strftime("%Y%m%d_%H%M%S"))
            capture()
            if os.environ.get("TPU_WATCH_ONCE") == "1":
                return
        time.sleep(interval)


if __name__ == "__main__":
    main()
