"""Runtime lock-order + contention smoke under the LockSentinel (tier-1).

The runtime complement of the flint concurrency rules (LCK01..LCK03):
install ONE :class:`flink_tpu.observe.LockSentinel` across the hot
multi-threaded surfaces and gate on what it actually observed:

1. **Cluster phase** — a session cluster runs TWO jobs while client
   threads hammer batched queryable-state lookups (the serving plane's
   coalescer/worker/cache locks all see cross-thread traffic). When the
   native hot cache is available the same cluster arms the shm serving
   tier and a 2-process :class:`FrontendPool` serves part of the load
   (the ``frontend.pipe`` dispatch locks join the graph); otherwise the
   frontend leg is LOUDLY skipped — the cluster gates still run.
2. **Backend churn phase** — threads race :func:`backend_scope` /
   :func:`set_backend` / :func:`backend_of` on the state-plane backend
   registry (the regression surface of the r24 thread-safety fix).
3. **Program-cache churn phase** — threads race ``get_or_build`` on a
   fresh :class:`SharedProgramCache` (same ``tenancy.program_cache``
   lock name): the once-latch protocol's release boundaries — the ones
   LCK03 suppresses by design argument — run under the sentinel.

The run FAILS on:

- ANY observed lock-order cycle (``sentinel.check`` — a cycle raised in
  a daemon thread is still recorded and still fails here),
- any single hold over ``LOCK_SMOKE_HOLD_BUDGET_S`` (default 2 s — a
  lock held across a compile or device call, not scheduler noise),
- fewer than 2 DISTINCT locks actually contended (vacuity: on the
  1-core box the phases above must produce real cross-thread traffic,
  or the whole order graph is an artifact of one thread),
- any expected lock family with zero acquisitions (unguarded-hit
  regression: a hot class quietly reverting ``named_lock`` to the bare
  primitive disappears from the sentinel — this gate notices),
- any client error or empty job output (the load must be real).

    JAX_PLATFORMS=cpu python tools/lock_smoke.py
    LOCK_SMOKE_RECORDS=... LOCK_SMOKE_CLIENTS=... to scale.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

RECORDS = int(os.environ.get("LOCK_SMOKE_RECORDS", 40_000))
CLIENTS = int(os.environ.get("LOCK_SMOKE_CLIENTS", 8))
KEYS = int(os.environ.get("LOCK_SMOKE_KEYS", 2048))
LOOKUP_BATCH = int(os.environ.get("LOCK_SMOKE_LOOKUP_BATCH", 128))
FRONTENDS = int(os.environ.get("LOCK_SMOKE_FRONTENDS", 2))
HOLD_BUDGET_S = float(os.environ.get("LOCK_SMOKE_HOLD_BUDGET_S", 2.0))
CHURN_THREADS = int(os.environ.get("LOCK_SMOKE_CHURN_THREADS", 4))
CHURN_ITERS = int(os.environ.get("LOCK_SMOKE_CHURN_ITERS", 400))

#: locks EXEMPT from the hold budget: 'frontend.pipe' serializes one
#: owner-side dispatcher onto a frontend's bounded request pipe — it
#: holds across a blocking IPC round trip BY DESIGN (one in-flight
#: request per frontend), so wall-clock holds there measure the
#: frontend's service time, not a forgotten critical section
HOLD_BUDGET_EXEMPT = frozenset({"frontend.pipe"})

#: lock families that MUST appear in the sentinel's accounting — each
#: tuple is alternatives (e.g. the cache plane is either the Python
#: LRU's lock or the native writer lock, depending on the build)
EXPECTED_LOCK_FAMILIES = [
    ("stateplane.backends",),
    ("tenancy.program_cache",),
    ("tenancy.hot_rows", "tenancy.native_cache"),
    ("serving.coalescer", "serving.worker", "serving.workers",
     "serving.pool"),
]


def _pipeline(sink):
    from flink_tpu.connectors.sinks import CollectSink  # noqa: F401
    from flink_tpu.connectors.sources import DataGenSource
    from flink_tpu.core.config import Configuration
    from flink_tpu.datastream.environment import StreamExecutionEnvironment
    from flink_tpu.runtime.watermarks import WatermarkStrategy
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 4096,
        "parallelism.default": 4,
        "serving.replica": True,
        "serving.replica.publish-interval-ms": 25,
    }))
    (env.add_source(
        DataGenSource(total_records=RECORDS, num_keys=KEYS,
                      events_per_second_of_eventtime=50_000, seed=13),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("key")
        .window(TumblingEventTimeWindows.of(60_000))
        .sum("value").sink_to(sink))
    return env


def cluster_phase(sentinel, tmp, frontend_armed):
    """Two jobs + concurrent lookup clients (+ frontend pool when the
    native shm cache exists). Returns (errors, sink_rows, fe_live)."""
    import warnings

    warnings.filterwarnings("ignore")
    import numpy as np

    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.tenancy.session_cluster import SessionCluster

    operator = "window_agg(SumAggregate)"
    cluster = SessionCluster(
        quantum_records=8192, serving_workers=2,
        serving_shm_dir=(os.path.join(tmp, "serving-shm")
                         if frontend_armed else None))
    s1, s2 = CollectSink(), CollectSink()
    cluster.submit(_pipeline(s1), "job-1")
    cluster.submit(_pipeline(s2), "job-2")
    pool = None
    if frontend_armed:
        from flink_tpu.tenancy.frontend import FrontendPool

        pool = FrontendPool(cluster.serving, n_frontends=FRONTENDS)

    stop = threading.Event()
    errors = []

    def client(i):
        rng = np.random.default_rng(300 + i)
        while not stop.is_set():
            job = "job-1" if i % 2 == 0 else "job-2"
            ks = rng.integers(0, KEYS, LOOKUP_BATCH).tolist()
            try:
                # odd clients route through the frontend pool when it
                # exists (the pipe-dispatch locks join the graph)
                if pool is not None and i % 2 == 1:
                    pool.lookup_batch(job, operator, ks)
                else:
                    cluster.lookup_batch(job, operator, ks)
            except (RuntimeError, TimeoutError) as e:
                msg = str(e)
                if ("is not serving" in msg
                        or "already terminated" in msg
                        or "shut down" in msg
                        or "FrontendPool is closed" in msg):
                    return  # job finished: lookups drain off
                errors.append(f"client {i}: {e!r}")
                return
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(CLIENTS)]
    for t in threads:
        t.start()
    fe_live = None
    try:
        cluster.run(timeout_s=600)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if pool is not None:
            fe_live = len(pool.live_frontends())
            pool.close()
            cluster.serving.hot_cache.close()
    return errors, len(s1.result()) + len(s2.result()), fe_live


def backend_churn_phase():
    """Threads race scope/set/read on the backend registry; the module
    lock ('stateplane.backends') must come out contended and the final
    state must be the default (no override leaked by a lost restore
    race the r24 compare-and-restore fix removed)."""
    from flink_tpu.stateplane.backends import (
        backend_of,
        backend_scope,
        set_backend,
    )

    errors = []

    def churn(i):
        try:
            for _ in range(CHURN_ITERS):
                if i % 2 == 0:
                    with backend_scope("exchange-rank", "pallas"):
                        backend_of("exchange-rank")
                else:
                    set_backend("exchange-rank", "pallas")
                    backend_of("exchange-rank")
                    set_backend("exchange-rank", "xla")
        except Exception as e:  # noqa: BLE001 - surfaced as a gate
            errors.append(f"backend churn {i}: {e!r}")

    _run_churn(churn, errors)
    set_backend("exchange-rank", "xla")  # deterministic end state
    return errors


def _run_churn(fn, errors):
    """Run ``fn(i)`` on CHURN_THREADS threads under a tiny GIL switch
    interval: the default 5 ms quantum lets a microsecond critical
    section finish unpreempted, so the contention the 1-core box CAN
    produce never shows — shrinking the quantum makes the interleaving
    real instead of making the gate vacuous."""
    prev = sys.getswitchinterval()
    sys.setswitchinterval(5e-5)
    try:
        threads = [threading.Thread(target=fn, args=(i,), daemon=True)
                   for i in range(CHURN_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    finally:
        sys.setswitchinterval(prev)
    return errors


def program_cache_churn_phase():
    """Threads race get_or_build on a fresh cache instance: the
    once-latch protocol (one builder per key, waiters re-probe) runs
    under the sentinel — same 'tenancy.program_cache' lock name."""
    from flink_tpu.tenancy.program_cache import SharedProgramCache

    cache = SharedProgramCache()
    errors = []
    built = {"n": 0}
    built_mu = threading.Lock()

    def builder_for(key):
        def build():
            time.sleep(0.001)  # a build long enough for waiters to park
            with built_mu:
                built["n"] += 1
            return ("program", key)
        return build

    def churn(i):
        try:
            for k in range(CHURN_ITERS // 4):
                got = cache.get_or_build("smoke", k, builder_for(k))
                if got != ("program", k):
                    errors.append(f"cache churn {i}: wrong value {got!r}")
                    return
        except Exception as e:  # noqa: BLE001 - surfaced as a gate
            errors.append(f"cache churn {i}: {e!r}")

    _run_churn(churn, errors)
    if built["n"] != CHURN_ITERS // 4 and not errors:
        errors.append(
            f"once-latch broke: {built['n']} builds for "
            f"{CHURN_ITERS // 4} keys (duplicate or lost builds)")
    return errors


def main():
    import tempfile

    from flink_tpu.native import hotcache_available
    from flink_tpu.observe import LockOrderViolation, LockSentinel

    frontend_armed = (hotcache_available()
                      and os.environ.get(
                          "FLINK_TPU_NATIVE_HOTCACHE") != "0")
    if not frontend_armed:
        print("LOCK SMOKE: native hotcache unavailable — frontend-pool "
              "leg SKIPPED (cluster/backend/cache gates still run)")

    sentinel = LockSentinel()
    with tempfile.TemporaryDirectory(prefix="lock_smoke_") as tmp:
        with sentinel:
            errors, rows, fe_live = cluster_phase(
                sentinel, tmp, frontend_armed)
            errors += backend_churn_phase()
            errors += program_cache_churn_phase()

    ok = True
    if errors:
        print(f"FAIL: {errors[:3]}")
        ok = False
    if rows == 0:
        print("FAIL: jobs produced no output — vacuous run")
        ok = False
    if frontend_armed and fe_live == 0:
        print("FAIL: every frontend died during the run")
        ok = False

    # gate 1: no observed order cycle
    try:
        sentinel.check()
    except LockOrderViolation as e:
        print(f"FAIL: {e}")
        ok = False

    rep = sentinel.report()
    locks = rep["locks"]

    # gate 1b: hold budget, minus the documented IPC-wait exemption
    over = sorted((n, st["hold_max_s"]) for n, st in locks.items()
                  if st["hold_max_s"] > HOLD_BUDGET_S
                  and n not in HOLD_BUDGET_EXEMPT)
    if over:
        print(f"FAIL: lock hold budget {HOLD_BUDGET_S:.3f}s exceeded: "
              f"{over}")
        ok = False

    # gate 2 (vacuity): >= 2 DISTINCT locks really contended — the
    # order graph of an uncontended run proves nothing
    contended = sentinel.contended_locks()
    if len(contended) < 2:
        print(f"FAIL: only {contended} contended — the smoke load is "
              "vacuous (no real cross-thread lock traffic)")
        ok = False

    # gate 3 (unguarded-hit regression): every expected family must
    # have been acquired through its NamedLock at least once
    for family in EXPECTED_LOCK_FAMILIES:
        hits = sum(locks.get(n, {}).get("acquisitions", 0)
                   for n in family)
        if hits == 0:
            print(f"FAIL: no acquisitions observed for any of "
                  f"{family} — a hot class reverted named_lock to the "
                  "bare primitive (unguarded-hit regression)")
            ok = False
    if frontend_armed:
        if locks.get("frontend.pipe", {}).get("acquisitions", 0) == 0:
            print("FAIL: frontend pool armed but 'frontend.pipe' never "
                  "acquired — the dispatch path went unobserved")
            ok = False

    print(json.dumps({
        "locks_observed": len(locks),
        "edges": len(rep["edges"]),
        "cycles": len(rep["cycles"]),
        "contended": contended,
        "hold_max_s": max((st["hold_max_s"] for st in locks.values()),
                          default=0.0),
        "frontend_armed": frontend_armed,
    }), flush=True)
    print(f"lock smoke: locks={len(locks)} edges={len(rep['edges'])} "
          f"cycles={len(rep['cycles'])} contended={len(contended)} "
          f"frontend={'armed' if frontend_armed else 'SKIPPED'} "
          f"=> {'OK' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
