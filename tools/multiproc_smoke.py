#!/usr/bin/env python
"""Multi-process pod smoke: 2 REAL CPU processes, one key-group space.

The ROADMAP item-2 acceptance oracle, executable on any dev box: two
processes (``jax.distributed.initialize`` + gloo CPU collectives), each
owning half the key-group space with its own session-metadata plane,
spill tier and per-range checkpoint units, exchange records over the
DCN axis of the process-spanning mesh ON DEVICE
(``parallel/pod.PodDataPlane``) and run the mesh_sessions shape.

FAILS on any of:

- output divergence: the union of the two processes' committed windows
  must be BIT-IDENTICAL to the single-process run of the same stream,
- steady-state compiles: the measured rep (fresh engines + fresh pod
  plane on the warm program cache) must compile NOTHING,
- a vacuous run: 0 rows crossed a process boundary on the device plane,
- the chaos leg: kill process 1 mid-stream — the survivor must restore
  ONLY the dead host's key-group ranges from its checkpoint units,
  replay no more than the per-host bound, and finish bit-identical.

Also emits the ``mesh_sessions_2proc`` bench numbers (aggregate ev/s +
scaling vs the same-box 1-process run) — honest caveat: on a 1-core CI
box two processes time-share one clock, so the aggregate measures
pod-protocol overhead, not the pod speedup a multi-core/multi-host box
shows (NOTES_r18.md).

    JAX_PLATFORMS=cpu python tools/multiproc_smoke.py
    MP_SMOKE_RECORDS=$((1<<17)) ... # scale knobs
"""
import json
import os
import socket
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

GAP = 40
SPAN = 80
MAXP = 128
HOSTS, LOCAL = 2, 4

RECORDS = int(os.environ.get("MP_SMOKE_RECORDS", str(1 << 16)))
BATCH = int(os.environ.get("MP_SMOKE_BATCH", "4096"))
KEYS = int(os.environ.get("MP_SMOKE_KEYS", str(max(RECORDS // 3, 64))))
SLOTS = int(os.environ.get("MP_SMOKE_SLOTS", "0"))
SEED = int(os.environ.get("MP_SMOKE_SEED", "23"))
KILL_AT = int(os.environ.get("MP_SMOKE_KILL_AT", "0"))  # child flag
CKPT_EVERY = int(os.environ.get("MP_SMOKE_CKPT_EVERY", "4"))
FINAL_WM = 1 << 60


def n_batches() -> int:
    return -(-RECORDS // BATCH)


def make_batch(b: int):
    """Global batch ``b`` — regenerable by ANY process from the seed
    (the replay path depends on this: a survivor rebuilds the dead
    host's range from the stream, not from the dead host)."""
    import numpy as np

    rng = np.random.default_rng(SEED * 1_000_003 + b)
    n = min(BATCH, RECORDS - b * BATCH)
    keys = rng.integers(0, KEYS, n).astype(np.int64)
    vals = rng.integers(0, 1000, n).astype(np.float32)
    ts = rng.integers(b * SPAN, b * SPAN + 60, n).astype(np.int64)
    return keys, vals, ts, (b - 1) * SPAN


def _keyed(keys, vals, ts):
    import numpy as np

    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )

    return RecordBatch({
        KEY_ID_FIELD: np.asarray(keys, dtype=np.int64),
        "v": np.asarray(vals, dtype=np.float32),
        TIMESTAMP_FIELD: np.asarray(ts, dtype=np.int64)})


def _collect(batches, into):
    from flink_tpu.core.records import KEY_ID_FIELD

    for b in batches:
        for r in b.to_rows():
            into[(int(r[KEY_ID_FIELD]), int(r["window_start"]),
                  int(r["window_end"]))] = float(r["sum_v"])


def _dump(path, committed, **extra):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"committed": [[k[0], k[1], k[2], v]
                                 for k, v in sorted(committed.items())],
                   **extra}, f)
    os.replace(tmp, path)


def _load_committed(path):
    with open(path) as f:
        d = json.load(f)
    return {(k, a, b): v for k, a, b, v in d["committed"]}, d


def _mk_engine(key_group_range=None):
    import jax

    from flink_tpu.parallel.mesh import make_mesh
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate

    return MeshSessionEngine(
        GAP, SumAggregate("v"),
        make_mesh(devices=jax.local_devices()),
        capacity_per_shard=1 << 14, max_device_slots=SLOTS,
        max_parallelism=MAXP, key_group_range=key_group_range,
        max_dispatch_ahead=2)


# --------------------------------------------------------------- children


def run_single(out_path: str) -> None:
    """1-process baseline: the full stream through one engine over the
    same per-process device count — the smoke's oracle AND the scaling
    row's denominator."""
    from flink_tpu.observe import compile_count

    def rep(commit: bool):
        committed = {}
        eng = _mk_engine()
        for b in range(n_batches()):
            keys, vals, ts, wm = make_batch(b)
            eng.process_batch(_keyed(keys, vals, ts))
            _collect(eng.on_watermark(wm), committed)
        _collect(eng.on_watermark(FINAL_WM), committed)
        return committed

    rep(False)                      # warmup: compiles + tier walk
    c0 = compile_count()
    t0 = time.perf_counter()
    committed = rep(True)           # measured: fresh engine, warm cache
    wall = time.perf_counter() - t0
    _dump(out_path, committed, wall_s=wall, events=RECORDS,
          compiles_measured=compile_count() - c0)


def run_pod(pid: int, port: int, out_path: str,
            ckpt_root: str) -> None:
    """One pod process: owns ``host_key_group_ranges[pid]``, exchanges
    the rest over the DCN axis, commits per checkpoint epoch. With
    KILL_AT > 0 this is the chaos leg: process 1 dies after batch
    KILL_AT; process 0 evacuates the dead host's ranges."""
    import numpy as np

    from flink_tpu.parallel.mesh import (
        HostTopology,
        initialize_distributed,
    )

    initialize_distributed(f"localhost:{port}", HOSTS, pid)

    from flink_tpu.checkpoint.sharded import ShardedCheckpointStorage
    from flink_tpu.observe import compile_count
    from flink_tpu.parallel.pod import PodDataPlane
    from flink_tpu.state.keygroups import (
        assign_key_groups,
        host_key_group_ranges,
        host_of_key_group,
    )

    topo = HostTopology(HOSTS, LOCAL)
    ranges = host_key_group_ranges(HOSTS, LOCAL, MAXP)
    my_range = ranges[pid]
    half = lambda b, n: (slice(0, n // 2) if pid == 0  # noqa: E731
                         else slice(n // 2, n))

    def owners_of(keys):
        return host_of_key_group(
            assign_key_groups(keys, MAXP), HOSTS, LOCAL, MAXP)

    progress = os.path.join(ckpt_root, f"host-{pid}.progress")
    tombstone = os.path.join(ckpt_root, "host-1.dead")
    storage = ShardedCheckpointStorage(
        os.path.join(ckpt_root, f"host-{pid}"))

    def rep(commit: bool, chaos: bool):
        committed, epoch = {}, {}
        eng = _mk_engine(my_range)
        plane = PodDataPlane(
            topo, dtypes=[np.int64, np.int64, np.float32],
            max_parallelism=MAXP)
        evac = None            # survivor's engine for the dead range
        cid = 0
        replayed = 0
        restored_units = 0
        for b in range(n_batches()):
            keys, vals, ts, wm = make_batch(b)
            if chaos and b > KILL_AT:
                if pid == 1:
                    return committed, plane, 0, 0
                if evac is None:
                    # the death notification (the deterministic chaos
                    # schedule stands in for the heartbeat timeout):
                    # restore ONLY the dead host's ranges from ITS
                    # checkpoint units, replay only its records
                    for _ in range(200):
                        if os.path.exists(tombstone):
                            break
                        time.sleep(0.05)
                    assert os.path.exists(tombstone), \
                        "peer never wrote its death marker"
                    dead_storage = ShardedCheckpointStorage(
                        os.path.join(ckpt_root, "host-1"))
                    found = dead_storage.read_all_units_with_fallback()
                    evac = _mk_engine(ranges[1])
                    if found is None:
                        unit_pos = 0
                    else:
                        _newest, units, _skipped = found
                        for r, _s, _p in units:
                            assert ranges[1][0] <= r[0] \
                                and r[1] <= ranges[1][1], \
                                f"unit {r} outside the dead range"
                        evac.restore(evac.merge_unit_snapshots(
                            [s for _r, s, _p in units]))
                        restored_units = len(units)
                        unit_pos = min(p for _r, _s, p in units)
                    # the dead host's committed output survives in its
                    # committed file; everything after its last
                    # checkpoint replays here (uncommitted epoch was
                    # rolled back with the process)
                    for rb in range(unit_pos, KILL_AT + 1):
                        rk, rv, rt, rwm = make_batch(rb)
                        mask = owners_of(rk) == 1
                        if mask.any():
                            evac.process_batch(_keyed(
                                rk[mask], rv[mask], rt[mask]))
                            replayed += int(mask.sum())
                        _collect(evac.on_watermark(rwm), epoch)
                # post-evacuation: the survivor owns everything — it
                # regenerates the FULL batch and routes host-side (the
                # DCN plane died with the peer)
                own = owners_of(keys)
                m0, m1 = own == 0, own == 1
                if m0.any():
                    eng.process_batch(_keyed(keys[m0], vals[m0],
                                             ts[m0]))
                if m1.any():
                    evac.process_batch(_keyed(keys[m1], vals[m1],
                                              ts[m1]))
                _collect(eng.on_watermark(wm), epoch)
                _collect(evac.on_watermark(wm), epoch)
            else:
                n = len(keys)
                sl = half(b, n)
                sub_k, sub_v, sub_t = keys[sl], vals[sl], ts[sl]
                # both processes regenerate the full batch, so the
                # chunk bound is deterministic — no agreement
                # collective per batch
                arrivals = plane.exchange(
                    owners_of(sub_k), [sub_k, sub_t, sub_v],
                    chunk_bound=-(-(n - n // 2) // LOCAL))
                ak, at, av = arrivals[plane.my_host]
                if len(ak):
                    eng.process_batch(_keyed(ak, av, at))
                _collect(eng.on_watermark(wm), epoch)
                with open(progress + ".tmp", "w") as f:
                    f.write(str(b))
                os.replace(progress + ".tmp", progress)
            if commit and (b + 1) % CKPT_EVERY == 0:
                cid += 1
                units = eng.snapshot_sharded()
                storage.write_checkpoint(
                    cid, f"pod-host-{pid}", units,
                    positions={r: b + 1 for r in units})
                committed.update(epoch)
                epoch = {}
                _dump(out_path, committed, final=False)
            if chaos and pid == 1 and b == KILL_AT:
                # die "mid-stream": after the batch's collective, with
                # an uncommitted epoch in flight — write the death
                # marker (the cluster manager's notification) and
                # vanish without a final flush
                with open(tombstone, "w") as f:
                    f.write(str(b))
                _dump(out_path, committed, final=False,
                      died_at=b)
                os._exit(0)
        _collect(eng.on_watermark(FINAL_WM), epoch)
        if evac is not None:
            _collect(evac.on_watermark(FINAL_WM), epoch)
        committed.update(epoch)
        return committed, plane, replayed, restored_units

    if KILL_AT:
        t0 = time.perf_counter()
        committed, plane, replayed, restored_units = rep(
            commit=True, chaos=True)
        wall = time.perf_counter() - t0
        _dump(out_path, committed, final=True, wall_s=wall,
              events=RECORDS, replayed=replayed,
              restored_units=restored_units,
              cross_rows=plane.rows_cross_host,
              intra_rows=plane.rows_intra_host)
        # the peer is dead: jax.distributed's shutdown barrier can
        # only fail (heartbeat timeout -> abort) — results are on
        # disk, leave without running it
        os._exit(0)

    rep(commit=False, chaos=False)  # warmup: compiles + tier walk
    c0 = compile_count()
    t0 = time.perf_counter()
    committed, plane, _, _ = rep(commit=True, chaos=False)
    wall = time.perf_counter() - t0
    _dump(out_path, committed, final=True, wall_s=wall,
          events=RECORDS,
          compiles_measured=compile_count() - c0,
          cross_rows=plane.rows_cross_host,
          intra_rows=plane.rows_intra_host)


# ----------------------------------------------------------------- parent


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(role, workdir, extra_env=None, **kw):
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("MP_SMOKE_CHILD_XLA", "")
        + " --xla_force_host_platform_device_count="
        + str(LOCAL)).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["MP_SMOKE_ROLE"] = role
    for k, v in kw.items():
        env[k.upper()] = str(v)
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env=env, cwd=workdir,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def _wait(procs, names, timeout=900):
    outs = {}
    deadline = time.time() + timeout
    for p, name in zip(procs, names):
        try:
            out, _ = p.communicate(timeout=max(deadline - time.time(),
                                               1))
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            raise SystemExit(
                f"MULTIPROC SMOKE: {name} timed out\n"
                + out.decode()[-2000:])
        outs[name] = out.decode()
        if p.returncode != 0:
            raise SystemExit(
                f"MULTIPROC SMOKE: {name} failed "
                f"(rc={p.returncode})\n" + outs[name][-2000:])
    return outs


def _merge_committed(parts):
    merged = {}
    for part in parts:
        for k, v in part.items():
            if k in merged and merged[k] != v:
                raise SystemExit(
                    f"MULTIPROC SMOKE: conflicting committed cell {k}:"
                    f" {merged[k]} vs {v}")
            merged[k] = v
    return merged


def main() -> int:
    import tempfile

    workdir = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    tmp = tempfile.mkdtemp(prefix="mp_smoke_")

    # ---- 1-process baseline (oracle + scaling denominator) ----
    single_out = os.path.join(tmp, "single.json")
    _wait([_spawn("single", workdir, mp_smoke_out=single_out)],
          ["single"])
    oracle, single_meta = _load_committed(single_out)
    if single_meta["compiles_measured"] != 0:
        raise SystemExit(
            "MULTIPROC SMOKE: single-process measured rep compiled "
            f"{single_meta['compiles_measured']} programs")

    # ---- 2-process scaling phase ----
    port = _free_port()
    outs = [os.path.join(tmp, f"pod-{i}.json") for i in range(HOSTS)]
    ck = os.path.join(tmp, "ck-scale")
    os.makedirs(ck, exist_ok=True)
    procs = [
        _spawn("pod", workdir, mp_smoke_out=outs[i],
               mp_smoke_pid=i, mp_smoke_port=port,
               mp_smoke_ckpt=ck)
        for i in range(HOSTS)]
    _wait(procs, [f"pod-{i}" for i in range(HOSTS)])
    parts, metas = zip(*[_load_committed(o) for o in outs])
    merged = _merge_committed(parts)
    if merged != oracle:
        extra = set(merged) - set(oracle)
        missing = set(oracle) - set(merged)
        wrong = [k for k in merged
                 if k in oracle and merged[k] != oracle[k]]
        raise SystemExit(
            "MULTIPROC SMOKE: 2-process output DIVERGED from the "
            f"single-process run ({len(missing)} missing, "
            f"{len(extra)} extra, {len(wrong)} wrong; e.g. "
            f"{(list(missing) + list(extra) + wrong)[:3]})")
    cross = sum(m["cross_rows"] for m in metas)
    intra = sum(m["intra_rows"] for m in metas)
    if cross == 0:
        raise SystemExit(
            "MULTIPROC SMOKE: vacuous — 0 rows crossed a process "
            "boundary on the device plane")
    compiles = sum(m["compiles_measured"] for m in metas)
    if compiles != 0:
        raise SystemExit(
            f"MULTIPROC SMOKE: measured rep compiled {compiles} "
            "programs (steady state must be 0)")
    wall_2p = max(m["wall_s"] for m in metas)
    ev_s_2p = RECORDS / wall_2p
    ev_s_1p = RECORDS / single_meta["wall_s"]
    scaling = ev_s_2p / ev_s_1p
    # the near-linear target (ROADMAP item 2) is gateable only where 2
    # processes get 2 clocks: a 1-core CI box time-shares them and
    # measures protocol overhead, not pod speedup (NOTES_r18.md) — so
    # the gate ARMS ITSELF when the affinity mask grants >= 2 CPUs
    # (1.4x default: two clocks minus the DCN/ICI protocol tax), and
    # stays env-overridable both ways (0 disarms, higher tightens)
    default_gate = ("1.4" if len(os.sched_getaffinity(0)) >= 2
                    else "0")
    min_scaling = float(os.environ.get("MP_SMOKE_MIN_SCALING",
                                       default_gate))
    if min_scaling and scaling < min_scaling:
        raise SystemExit(
            f"MULTIPROC SMOKE: scaling {scaling:.2f}x under the "
            f"{min_scaling}x gate")

    # ---- chaos phase: kill process 1 mid-stream ----
    port = _free_port()
    kill_at = max(n_batches() * 2 // 3, CKPT_EVERY + 1)
    if kill_at >= n_batches() - 1:
        raise SystemExit(
            f"MULTIPROC SMOKE: shape too small — {n_batches()} "
            f"batches cannot host a mid-stream kill at {kill_at} "
            "(raise MP_SMOKE_RECORDS or lower MP_SMOKE_BATCH)")
    ck = os.path.join(tmp, "ck-chaos")
    os.makedirs(ck, exist_ok=True)
    outs_c = [os.path.join(tmp, f"chaos-{i}.json")
              for i in range(HOSTS)]
    procs = [
        _spawn("pod", workdir, mp_smoke_out=outs_c[i],
               mp_smoke_pid=i, mp_smoke_port=port,
               mp_smoke_ckpt=ck, mp_smoke_kill_at=kill_at)
        for i in range(HOSTS)]
    _wait(procs, [f"chaos-{i}" for i in range(HOSTS)])
    dead_part, dead_meta = _load_committed(outs_c[1])
    surv_part, surv_meta = _load_committed(outs_c[0])
    merged_c = _merge_committed([dead_part, surv_part])
    if merged_c != oracle:
        missing = set(oracle) - set(merged_c)
        extra = set(merged_c) - set(oracle)
        wrong = [k for k in merged_c
                 if k in oracle and merged_c[k] != oracle[k]]
        raise SystemExit(
            "MULTIPROC SMOKE: chaos output DIVERGED "
            f"({len(missing)} missing, {len(extra)} extra, "
            f"{len(wrong)} wrong)")
    if surv_meta["restored_units"] < 1:
        raise SystemExit(
            "MULTIPROC SMOKE: the survivor restored no checkpoint "
            "units — the dead host's state was rebuilt from nothing")
    if not (0 < surv_meta["replayed"] <= RECORDS // 2):
        raise SystemExit(
            f"MULTIPROC SMOKE: replay {surv_meta['replayed']} outside "
            f"the per-host bound (0, {RECORDS // 2}]")

    row = {
        "metric": "mesh_sessions_2proc_events_per_s",
        "value": round(ev_s_2p, 1),
        "single_proc_events_per_s": round(ev_s_1p, 1),
        "scaling_x": round(scaling, 3),
        "records": RECORDS,
        "cross_host_rows": cross,
        "intra_host_rows": intra,
        "chaos_replayed": surv_meta["replayed"],
        "chaos_restored_units": surv_meta["restored_units"],
        "chaos_recovery_bound": RECORDS // 2,
        "shape": (f"{RECORDS:,} records, 2 processes x {LOCAL} "
                  f"devices, sessions gap {GAP}; kill-1-of-2 "
                  "scenario bit-identical"),
    }
    print(json.dumps(row))
    print(f"MULTIPROC SMOKE OK: 2-proc {ev_s_2p:,.0f} ev/s vs 1-proc "
          f"{ev_s_1p:,.0f} ev/s ({scaling:.2f}x), "
          f"{cross:,} cross-host rows on the device plane, 0 "
          f"steady-state compiles, chaos leg restored "
          f"{surv_meta['restored_units']} units / replayed "
          f"{surv_meta['replayed']:,} records, all bit-identical")
    return 0


if __name__ == "__main__":
    role = os.environ.get("MP_SMOKE_ROLE", "parent")
    if role == "single":
        run_single(os.environ["MP_SMOKE_OUT"])
    elif role == "pod":
        KILL_AT = int(os.environ.get("MP_SMOKE_KILL_AT", "0"))
        run_pod(int(os.environ["MP_SMOKE_PID"]),
                int(os.environ["MP_SMOKE_PORT"]),
                os.environ["MP_SMOKE_OUT"],
                os.environ["MP_SMOKE_CKPT"])
    else:
        sys.exit(main())
