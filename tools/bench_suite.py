"""Measure every BASELINE.md row on the active backend.

Rows (BASELINE.json):
  1. WordCount, 5 s tumbling window, socket source
  2. Nexmark Q5 — sliding-window (HOP) hot-items COUNT   (bench.py's row)
  3. Nexmark Q7 — tumbling-window MAX + join
  4. Flink SQL GROUP BY HOP over Kafka
  5. Session-window clickstream, 10M distinct keys (spill tier)

Prints one JSON line per row and rewrites BENCHMARKS.md. Usage:

    BENCH_SKIP_PROBE=1 JAX_PLATFORMS=cpu python tools/bench_suite.py
    python tools/bench_suite.py          # probes the TPU first
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("BENCH_PROBE_TIMEOUTS", "45,120")

SCALE = float(os.environ.get("BENCH_SUITE_SCALE", "1.0"))


def _platform():
    import jax

    return jax.devices()[0].platform


def row1_wordcount():
    """Socket-source WordCount (the reference's WindowWordCount)."""
    import socket
    import threading

    from flink_tpu import Configuration, StreamExecutionEnvironment
    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.connectors.sources import SocketSource
    from flink_tpu.windowing.assigners import TumblingProcessingTimeWindows

    n_lines = int(200_000 * SCALE)
    line = b"to be or not to be that is the question\n"
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def feed():
        conn, _ = srv.accept()
        chunk = line * 512
        sent = 0
        while sent < n_lines:
            conn.sendall(chunk)
            sent += 512
        conn.close()

    t = threading.Thread(target=feed, daemon=True)
    t.start()
    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 1 << 15}))
    sink = CollectSink()

    def split(batch):
        import numpy as np

        from flink_tpu.core.records import RecordBatch

        words = []
        for ln in batch["line"]:
            words.extend(str(ln).split())
        arr = np.empty(len(words), dtype=object)
        arr[:] = words
        return RecordBatch({"word": arr,
                            "one": np.ones(len(words), dtype=np.int64)})

    (env.add_source(SocketSource("127.0.0.1", port))
        .flat_map(lambda b: [split(b)])
        .key_by("word")
        .window(TumblingProcessingTimeWindows.of(5_000))
        .sum("one").sink_to(sink))
    t0 = time.perf_counter()
    result = env.execute("wordcount")
    dt = time.perf_counter() - t0
    words = n_lines * 10
    return {"metric": "wordcount_socket_words_per_sec",
            "value": round(words / dt, 1), "unit": "words/s",
            "fire_latency_ms": result.metrics.get(
                "window_fire_latency_ms")}


def row2_q5():
    from bench import run

    run(total_records=1 << 21)  # warm
    s = run(total_records=int(20_000_000 * SCALE))
    return {"metric": "nexmark_q5_hop_hot_items_events_per_sec_per_chip",
            "value": round(s["events_per_s"], 1), "unit": "events/s",
            "fire_latency_ms": s["fire_latency_ms"]}


def row3_q7():
    from flink_tpu import Configuration, StreamExecutionEnvironment
    from flink_tpu.benchmarks.nexmark import BidSource, build_q7
    from flink_tpu.connectors.sinks import CollectSink

    def run(total):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1 << 16,
            "state.slot-table.capacity": 1 << 20}))
        sink = CollectSink()
        src = BidSource(total_records=total, num_auctions=10_000,
                        events_per_second_of_eventtime=100_000)
        # 2 s windows (was 10 s): the 10 s shape fired only 10 windows
        # over the row's 100 s of event time, so its percentiles were
        # VACUOUS (n=10, p99 == the single worst sample). 2 s gives
        # n >= 30 fires — the floor below which the suite flags a row's
        # fire percentiles low-confidence.
        build_q7(env, src, size_ms=2_000).sink_to(sink)
        t0 = time.perf_counter()
        result = env.execute("q7")
        return (total / (time.perf_counter() - t0),
                result.metrics.get("window_fire_latency_ms"))

    run(1 << 20)  # warm
    total = int(10_000_000 * SCALE)
    evps, lat = run(total)
    # fire percentiles on EVERY windowed row: the matrix stays
    # comparable (q5 reported them, q7 did not — and the latency-tier
    # gate of ROADMAP item 2 needs this hook on each row)
    return {"metric": "nexmark_q7_max_join_events_per_sec_per_chip",
            "value": round(evps, 1), "unit": "events/s",
            "fire_latency_ms": lat}


def row4_sql_hop_kafka():
    import numpy as np

    from flink_tpu import Configuration, StreamExecutionEnvironment
    from flink_tpu.connectors.kafka import FakeBroker
    from flink_tpu.core.records import RecordBatch
    from flink_tpu.table.environment import StreamTableEnvironment

    total = int(8_000_000 * SCALE)
    parts = 4
    broker = FakeBroker.get("bench")
    broker.create_topic("bench_bids", parts)
    rng = np.random.default_rng(1)
    chunk = 1 << 18
    produced = 0
    while produced < total:
        n = min(chunk, total - produced)
        ks = rng.integers(0, 10_000, n).astype(np.int64)
        vs = rng.random(n)
        ts = (np.arange(produced, produced + n, dtype=np.int64)
              * 1000) // 100_000
        for p in range(parts):
            m = ks % parts == p
            broker.append("bench_bids", p, RecordBatch.from_pydict(
                {"key": ks[m], "value": vs[m], "ts": ts[m]},
                timestamps=ts[m]))
        produced += n

    def run():
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1 << 16}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql(
            "CREATE TABLE bench_bids (key BIGINT, value DOUBLE, "
            "ts BIGINT, WATERMARK FOR ts AS ts) "
            "WITH ('connector'='kafka', 'topic'='bench_bids', "
            "'broker'='bench')")
        t0 = time.perf_counter()
        rows = tenv.execute_sql("""
            SELECT key, window_end, SUM(value) AS total
            FROM TABLE(HOP(TABLE bench_bids, DESCRIPTOR(ts),
                           INTERVAL '2' SECOND, INTERVAL '10' SECONDS))
            GROUP BY key, window_start, window_end
        """).collect()
        dt = time.perf_counter() - t0
        assert len(rows) > 0
        # the SQL collect path runs env.execute internally; the env
        # keeps the job result so windowed SQL rows report fire
        # percentiles like the DataStream rows
        res = getattr(env, "last_execution_result", None)
        return (total / dt,
                res.metrics.get("window_fire_latency_ms")
                if res is not None else None)

    run()  # warm
    evps, lat = run()
    return {"metric": "sql_group_by_hop_over_kafka_events_per_sec",
            "value": round(evps, 1), "unit": "events/s",
            "fire_latency_ms": lat}


def row5_sessions_10m_keys():
    from flink_tpu import Configuration, StreamExecutionEnvironment
    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.connectors.sources import DataGenSource
    from flink_tpu.runtime.watermarks import WatermarkStrategy
    from flink_tpu.windowing.assigners import EventTimeSessionWindows

    total = int(12_000_000 * SCALE)
    keys = 10_000_000

    def run(n):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1 << 16,
            "state.slot-table.capacity": 1 << 19,
            "state.slot-table.max-device-slots": 1 << 19,
        }))
        sink = CollectSink()
        # THRASHING shape (BASELINE row 5): 400k ev/s of event time x
        # 2 s gap ~= 800k concurrently-live sessions vs the 512k device
        # slot budget — the live set EXCEEDS the device, so the run
        # exercises the paged spill tier (slot_table.py
        # spill_layout="pages") under sustained pressure, across ~10M
        # distinct keys. Rounds <= 4 measured a softened 200k ev/s
        # in-budget shape; those numbers are NOT comparable.
        src = DataGenSource(total_records=n, num_keys=keys,
                            events_per_second_of_eventtime=400_000,
                            seed=3)
        (env.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0))
           .key_by("key")
           .window(EventTimeSessionWindows.with_gap(2_000))
           .sum("value").sink_to(sink))
        t0 = time.perf_counter()
        result = env.execute("sessions")
        dt = time.perf_counter() - t0
        assert len(sink.result()) > 0
        return n / dt, result.metrics.get("window_fire_latency_ms")

    run(1 << 20)  # warm
    evps, lat = run(total)
    return {"metric":
            "session_clickstream_10m_keys_events_per_sec_per_chip",
            "value": round(evps, 1), "unit": "events/s",
            "fire_latency_ms": lat,
            "shape": "400k ev/s event time, 2 s gap, ~800k live "
                     "sessions vs 512k device budget (paged spill), "
                     "10M distinct keys"}


def row5b_mesh_sessions():
    """Row 5 on the MESH session engine (paged spill per shard) — runs
    in a subprocess so the CPU virtual-device flag the mesh needs cannot
    perturb the single-device rows' XLA threading."""
    import subprocess

    env = dict(os.environ)
    env.setdefault("BENCH_MESH_SESSION_RECORDS",
                   str(int(4_000_000 * SCALE)))
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "bench_mesh_sessions.py")],
        capture_output=True, text=True, env=env, timeout=3600)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    if proc.returncode != 0 or not lines:
        raise RuntimeError((proc.stderr or proc.stdout).strip()[-300:])
    return json.loads(lines[-1])


def row5c_mesh_sessions_zipf():
    """Row 5's shape with Zipf(1.1) keys and the skew-adaptive plane
    live (load accounting -> key-group moves -> hot-key splitting);
    reports the recovered fraction of the uniform control's
    throughput. Subprocess for the virtual-device flag, like row5b."""
    import subprocess

    env = dict(os.environ)
    env.setdefault("BENCH_MESH_SESSION_RECORDS",
                   str(int(4_000_000 * SCALE)))
    env["BENCH_MESH_ZIPF"] = "1"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "bench_mesh_sessions.py"), "--zipf"],
        capture_output=True, text=True, env=env, timeout=3600)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    if proc.returncode != 0 or not lines:
        raise RuntimeError((proc.stderr or proc.stdout).strip()[-300:])
    r = json.loads(lines[-1])
    sk = r.get("skew") or {}
    r["shape"] = (
        f"{r['shape']}; recovered "
        f"{r['skew_recovery_fraction']:.2f}x of uniform "
        f"({r['uniform_events_per_s']:,.0f} ev/s), "
        f"{sk.get('rebalances', 0)} rebalances / "
        f"{sk.get('groups_moved', 0)} groups moved / "
        f"{sk.get('keys_split', 0)} keys split "
        f"({sk.get('salted_records', 0):,} records salted), "
        f"imbalance {sk.get('imbalance_contiguous', 0)} -> "
        f"{sk.get('imbalance_live', 0)}")
    return r


def row6_queryable_lookups():
    """High-QPS queryable-state serving: 2 concurrent jobs on one mesh,
    client threads issuing 256-key batched point lookups (the tenancy
    serving plane). Subprocess for the virtual-device flag, like the
    mesh row."""
    import subprocess

    env = dict(os.environ)
    env.setdefault("SERVING_SMOKE_RECORDS",
                   str(int(400_000 * SCALE)))
    env.setdefault("SERVING_SMOKE_CLIENTS", "16")
    env.setdefault("SERVING_SMOKE_LOOKUP_BATCH", "256")
    env.setdefault("SERVING_SMOKE_KEYS", "4096")
    # the r19 native-fast-path operating point: 2 ms client pause (the
    # packed path holds the staleness SLO there; the dict control does
    # NOT — its recorded number stays at its own best point, 5 ms)
    env.setdefault("SERVING_SMOKE_CLIENT_PAUSE_MS", "2")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "serving_smoke.py")],
        capture_output=True, text=True, env=env, timeout=3600)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    if proc.returncode != 0 or not lines:
        raise RuntimeError((proc.stderr or proc.stdout).strip()[-300:])
    return json.loads(lines[-1])


def row7_shard_loss_recovery():
    """Partial failover: kill 1 of 4 shards mid-stream (the chaos
    smoke's shard-loss scenario at bench scale — 1M events, forced
    paged eviction) and report wall-clock recovery: survivor
    evacuation + mesh rebuild + checkpoint-unit restore of ONLY the
    dead range + bounded replay of ONLY its records."""
    import subprocess

    env = dict(os.environ)
    env.setdefault("CHAOS_SHARD_LOSS_KEYS",
                   str(int(1_000_000 * SCALE)))
    env.setdefault("CHAOS_SHARD_LOSS_PER_STEP",
                   str(int(125_000 * SCALE)))
    env.setdefault("CHAOS_SHARD_LOSS_SLOTS", str(1 << 14))
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.argv=['chaos_smoke']; "
         "import tools.chaos_smoke as cs; "
         "sys.exit(cs.shard_loss_scenario())"],
        capture_output=True, text=True, env=env, timeout=3600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    if proc.returncode != 0 or not lines:
        raise RuntimeError((proc.stderr or proc.stdout).strip()[-300:])
    r = json.loads(lines[-1])
    return {
        "metric": "shard_loss_recovery_ms",
        "value": r["shard_loss_recovery_ms"],
        "shape": (f"{r['events']:,} events over {r['shards']} shards, "
                  f"1 shard killed mid-stream (device.lost): "
                  f"{r['shard_restores']} range restored from its "
                  f"checkpoint unit, {r['records_replayed']:,} records "
                  f"replayed (bound: events/shards = "
                  f"{r['events'] // r['shards']:,}), output "
                  "oracle-identical"),
    }


def row8_mesh_sessions_2proc():
    """Pod-scale row: the mesh_sessions shape split across 2 REAL
    processes (jax.distributed + gloo CPU collectives), each owning
    half the key-group space with its own metadata plane, spill tier
    and checkpoint units, exchanging records over the DCN axis of the
    process-spanning mesh ON DEVICE (tools/multiproc_smoke.py). The
    row records the aggregate throughput and the scaling factor vs the
    same-box 1-process run — near-linear on real multi-core/multi-host
    boxes; a 1-core CI box time-shares the clock and reports the
    pod-protocol overhead instead (NOTES_r18.md)."""
    import subprocess

    env = dict(os.environ)
    env.setdefault("MP_SMOKE_RECORDS", str(int(262_144 * SCALE)))
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "multiproc_smoke.py")],
        capture_output=True, text=True, env=env, timeout=3600)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    if proc.returncode != 0 or not lines:
        raise RuntimeError((proc.stderr or proc.stdout).strip()[-300:])
    r = json.loads(lines[-1])
    r["unit"] = "events/s aggregate"
    r["shape"] += (
        f"; 1-proc same-box {r['single_proc_events_per_s']:,.0f} ev/s "
        f"-> scaling {r['scaling_x']}x, "
        f"{r['cross_host_rows']:,} rows crossed the DCN axis")
    return r


def row9_serving_mp():
    """Serving-tier row: N frontend PROCESSES attach the owner's shm
    hot-cache arena (tools/bench_serving_mp.py) and run the probe →
    packed-reply loop entirely in their own address space — no GIL
    shared with the owner, no pipe on the hit path — while the owner
    keeps priming fresh generations at the publish cadence. The row
    records the aggregate shm lookups/s off the SHARED arena header
    counters (fe_stats, not wall division) and the scaling factor vs
    the owner's own 1-process packed loop; near-linear on multi-core
    boxes, time-shared on a 1-core CI box (NOTES_r21.md)."""
    import subprocess

    env = dict(os.environ)
    env.setdefault("BENCH_SERVING_MP_BATCHES",
                   str(int(2000 * SCALE)))
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "bench_serving_mp.py")],
        capture_output=True, text=True, env=env, timeout=3600)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    if proc.returncode != 0 or not lines:
        raise RuntimeError((proc.stderr or proc.stdout).strip()[-300:])
    return json.loads(lines[-1])


def _join_rows():
    """Both join rows from tools/bench_joins.py in ONE subprocess (the
    mesh needs the virtual-device flag, like row5b; the tool prints one
    JSON line per row)."""
    import subprocess

    env = dict(os.environ)
    env.setdefault("BENCH_JOIN_RECORDS", str(int(4_000_000 * SCALE)))
    env.setdefault("BENCH_JOIN_REQUIRE_SPILL", "1")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "bench_joins.py")],
        capture_output=True, text=True, env=env, timeout=3600)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    if proc.returncode != 0 or len(lines) < 2:
        raise RuntimeError((proc.stderr or proc.stdout).strip()[-300:])
    return [json.loads(ln) for ln in lines[-2:]]


def row_cep():
    """Device-vectorized CEP at the row-5 thrashing shape: a 2-stage
    within-window sequence over 10M keys, live partials >> device
    budget (forced paged eviction), raced against the host CepOperator
    oracle at the same shape — the bench FAILS itself if the device
    engine loses or the spill tier never engages. Subprocess for the
    virtual-device flag, like row5b."""
    import subprocess

    env = dict(os.environ)
    env.setdefault("BENCH_CEP_RECORDS", str(int(4_000_000 * SCALE)))
    env.setdefault("BENCH_CEP_REQUIRE_SPILL", "1")
    env.setdefault("BENCH_CEP_REQUIRE_WIN", "1")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "bench_cep.py")],
        capture_output=True, text=True, env=env, timeout=3600)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    if proc.returncode != 0 or not lines:
        raise RuntimeError((proc.stderr or proc.stdout).strip()[-300:])
    return json.loads(lines[-1])


_JOIN_CACHE = {}


def _join_row(idx):
    def run():
        if "rows" not in _JOIN_CACHE:
            _JOIN_CACHE["rows"] = _join_rows()
        return _JOIN_CACHE["rows"][idx]

    return run


ROWS = [("wordcount_socket", row1_wordcount),
        ("nexmark_q5", row2_q5),
        ("nexmark_q7", row3_q7),
        ("sql_hop_kafka", row4_sql_hop_kafka),
        ("sessions_10m_keys", row5_sessions_10m_keys),
        ("mesh_sessions_10m_keys", row5b_mesh_sessions),
        ("mesh_sessions_zipf", row5c_mesh_sessions_zipf),
        ("queryable_lookups", row6_queryable_lookups),
        ("shard_loss_recovery", row7_shard_loss_recovery),
        ("nexmark_q8_windowed_join", _join_row(0)),
        ("interval_join_10m_keys", _join_row(1)),
        ("cep_patterns_10m_keys", row_cep),
        ("mesh_sessions_2proc", row8_mesh_sessions_2proc),
        ("serving_mp_lookups", row9_serving_mp)]


def main():
    import warnings

    warnings.filterwarnings("ignore")
    if os.environ.get("BENCH_SKIP_PROBE") != "1":
        from bench import probe_backend

        ok, info = probe_backend()
        if not ok:
            os.environ["JAX_PLATFORMS"] = "cpu"
    from flink_tpu.platform import sync_platform

    sync_platform()
    platform = _platform()
    results = []
    for name, fn in ROWS:
        try:
            r = fn()
        except Exception as e:  # noqa: BLE001 — a row must not kill the suite
            r = {"metric": name, "error": repr(e)}
        r["backend"] = platform
        lat = r.get("fire_latency_ms")
        if lat and lat.get("count", 0) < 30:
            # a windowed row that fired < 30 times has vacuous
            # percentiles (p99 == the worst 1-2 samples): flag it so
            # nobody gates or compares against noise
            r["fire_latency_low_confidence"] = True
        results.append(r)
        print(json.dumps(r), flush=True)
    lines = [
        "# BENCHMARKS — all BASELINE.md rows",
        "",
        f"Backend: **{platform}** · scale {SCALE} · "
        f"{time.strftime('%Y-%m-%d %H:%M')}",
        "",
        "| Row | Metric | Value | Unit |",
        "|---|---|---|---|",
    ]
    for (name, _), r in zip(ROWS, results):
        val = (f"{r['value']:,.0f}" if "value" in r
               else f"error: {r.get('error', '?')[:60]}")
        extra = ""
        if r.get("shape"):
            extra = f" — {r['shape']}"
        if r.get("spill"):
            sp = r["spill"]
            extra += (f" — spill: {sp['pages_evicted']} pages evicted, "
                      f"{sp['pages_reloaded']} reloaded, "
                      f"{sp['rows_split_on_reload']} rows split on "
                      f"reload, {sp.get('rows_compacted', 0)} compacted")
        if r.get("breakdown"):
            bd = r["breakdown"]
            if "host_prep_s" in bd:
                extra += (f" — host-prep {bd['host_prep_s']}s / "
                          f"device-step {bd['device_step_s']}s / harvest "
                          f"{bd['harvest_s']}s of {bd['total_s']}s")
                if "host_prep_fraction" in bd:
                    extra += (f" (host-prep fraction "
                              f"{bd['host_prep_fraction']})")
                if bd.get("native_sweep_s"):
                    extra += (f", native sweeps {bd['native_sweep_s']}s")
            elif "ingest_s" in bd:  # the join benches' phase split
                extra += (f" — ingest {bd['ingest_s']}s / probe+fire "
                          f"{bd['probe_fire_s']}s / harvest "
                          f"{bd['harvest_s']}s of {bd['total_s']}s")
        if r.get("shuffle_mode"):
            extra += f", {r['shuffle_mode']}-mode shuffle"
        if r.get("matches"):
            extra += f" — {r['matches']:,} matches"
        if r.get("fire_latency_ms"):
            lat = r["fire_latency_ms"]
            conf = (" LOW-CONFIDENCE (n<30)"
                    if r.get("fire_latency_low_confidence") else "")
            extra += (f" (fire p50 {lat['p50']:.0f} ms / "
                      f"p99 {lat['p99']:.0f} ms, n={lat['count']}{conf})")
        lines.append(f"| {name} | {r['metric']} | {val}{extra} | "
                     f"{r.get('unit', '')} |")
    lines.append("")
    lines.append("Generated by `tools/bench_suite.py`; the proxy "
                 "baseline discussion lives in `BASELINE.md`.")
    lines.append("")
    lines.append(
        "Methodology: headline values are the MEDIAN of post-warm reps "
        "(`bench.py` and `tools/bench_mesh_sessions.py`; best/all reps "
        "travel as secondary JSON fields). The mesh-sessions row drives "
        "the mesh engine's pipelined path (dispatch-ahead + async "
        "coalesced fire harvests) on 8 virtual CPU devices sharing one "
        "host's cores — a kernel-overhead lower bound; on TPU hardware "
        "the shards are real chips and the budget is per-chip HBM. Its "
        "spill counters come from the lazy-tombstone paged tier "
        "(NOTES_r6.md): `rows_split_on_reload` stays ~0 by design, and "
        "`tools/tier1.sh` gates on the page-rewrite amplification "
        "`(rows_split_on_reload + rows_compacted) / rows_reloaded`.")
    lines.append("")
    lines.append(
        "Fused-path methodology (r11): the mesh-sessions row runs "
        "`shuffle.mode=device` — flat columns go up in ONE `device_put` "
        "and a single compiled program segment-sorts, "
        "`all_to_all`-exchanges and scatter-aggregates them "
        "(`parallel/shuffle.py`; design in NOTES_r11.md). The breakdown "
        "attributes device work surfacing inside `process_batch` "
        "(dispatch-fence blocks + the engine-timed inline device "
        "interactions) to `device_step_s`, so `host_prep_fraction` "
        "measures genuine host work (sessionization, slot resolution, "
        "flat staging); `tools/tier1.sh` gates it via "
        "`BENCH_HOST_PREP_BUDGET` in device mode.")
    lines.append("")
    lines.append(
        "Native metadata plane (r12): the mesh-sessions row runs the "
        "session metadata (sessionize -> absorb -> slot-resolve -> pop) "
        "as ONE C sweep per batch (`native/sessions.cpp` via "
        "`windowing/session_native.py`; design in NOTES_r12.md), with "
        "the session's device slot FOLDED into its metadata row so "
        "singleton sessions skip the state-plane hash probe "
        "(fold-verify: a stale fold falls back to the probe, never a "
        "wrong row). `native_sweep_s` reports the C share of the "
        "breakdown; `native_session_plane` in the row JSON says which "
        "plane ran, and the tier-1 smoke FAILS if the native plane was "
        "requested but unavailable. The pure-Python plane "
        "(`FLINK_TPU_NATIVE_SESSIONS=0`) is bit-identical in fires, "
        "snapshots and spill counters (test-pinned).")
    lines.append("")
    lines.append(
        "The queryable-lookups row is `tools/serving_smoke.py` at bench "
        "scale: two concurrent ingesting jobs share one mesh and the "
        "compiled-program cache while client threads issue batched "
        "point lookups through the READ-REPLICA serving plane (r17) "
        "and, since r19, the NATIVE FAST PATH: the whole key batch "
        "probes a GIL-free seqlock-stamped table of PACKED composed "
        "results (`native/hotcache.cpp`) in ONE C call, hit results "
        "stay packed until a consumer reads them "
        "(`lookup_batch_packed`), the publish harvest primes via one "
        "packed buffer, and session entries re-prime under their "
        "MOVING end instead of invalidating. Methodology: the headline "
        "runs at the fast path's operating point (2 ms client pause); "
        "the same-box control (`FLINK_TPU_NATIVE_HOTCACHE=0` + "
        "`SERVING_SMOKE_PACKED=0`, the r17 path) is recorded at ITS "
        "best operating point that still holds the replica staleness "
        "SLO (5 ms pause — at 2 ms the GIL-held dict path starves the "
        "publish loop to seconds of staleness and is rejected), so "
        "both numbers describe a plane that actually serves fresh "
        "boundaries. The tier-1 smoke runs the same script smaller and "
        "FAILS on any steady-state compile, p99 over 25 ms, throughput "
        "under 350k lookups/s, a native hit path < 2x cheaper than the "
        "Python dict path (per-hit microbench), staleness p99 over "
        "1 s, a packed-vs-dict mismatch, a silent Python-cache "
        "fallback when the native library built, vacuous cache/publish "
        "activity, or a quota violation (design notes in NOTES_r10.md, "
        "NOTES_r17.md and NOTES_r19.md).")
    lines.append("")
    lines.append(
        "Pod scale (r18): the mesh_sessions_2proc row is "
        "`tools/multiproc_smoke.py` at bench scale — 2 REAL processes "
        "(`jax.distributed.initialize` + gloo CPU collectives), each "
        "owning half the key-group space (`host_key_group_ranges`) "
        "with its own session-metadata plane, spill tier and per-range "
        "checkpoint units; records reach their owner over the DCN axis "
        "of the process-spanning mesh ON DEVICE "
        "(`parallel/pod.PodDataPlane`), and each process's fused "
        "exchange is the intra-host ICI stage. The row reports "
        "aggregate ev/s and the scaling factor vs the same-box "
        "1-process run. CAVEAT: on a 1-core CI box both processes "
        "time-share one clock, so the scaling factor there measures "
        "pod-protocol overhead (exchange + harvest + re-stage), not "
        "the near-linear speedup a multi-core/multi-host box shows; "
        "the smoke's correctness gates (bit-identity, 0 steady-state "
        "compiles, cross-host traffic, kill-1-of-2 recovery) hold "
        "regardless (NOTES_r18.md).")
    lines.append("")
    lines.append(
        "Skew-adaptive plane (r20): the mesh_sessions_zipf row is "
        "`tools/bench_mesh_sessions.py --zipf` — the same 10M-key "
        "shape with the key column drawn Zipf(1.1), so a handful of "
        "keys carry most of the stream and the contiguous key-group "
        "layout pins one shard. The driver wires the skew ladder "
        "(detect -> rebalance -> split): `parallel/load.py` accounts "
        "per-key-group load from routed batches (EWMA + a Misra-Gries "
        "hot-key sketch), `autoscale/rebalance.py` plans greedy "
        "hottest-group-to-coldest-shard MOVES (hysteresis + cooldown) "
        "applied live via `reassign_key_groups` (P unchanged, same "
        "handoff discipline as reshard, own chaos fault point), and "
        "keys that dominate their group — which no group move can fix "
        "— are SPLIT via `register_hot_key`: records salt into "
        "sub-rows pre-aggregated on their own shards and fold back at "
        "fire in a fixed order (bit-identical for min/max/integer "
        "sums; float sums opt in via allow_inexact). The row reports "
        "zipf throughput, the uniform control, their ratio "
        "(`skew_recovery_fraction`) and the responder counters; "
        "`tools/tier1.sh` runs the same plane smaller via "
        "`tools/skew_smoke.py` and FAILS if recovery drops below "
        "`BENCH_SKEW_RECOVERY`, if no live move happened, if nothing "
        "was salted, or if the rebalanced/salted output diverges from "
        "the single-device oracle (NOTES_r20.md).")
    lines.append("")
    lines.append(
        "Multi-process serving tier (r21): the serving_mp_lookups row "
        "is `tools/bench_serving_mp.py` — N frontend PROCESSES "
        "(`tenancy/frontend.py FrontendPool`) attach the owner's "
        "hot-cache arena over shared memory (`hc_attach` on the "
        "contiguous mmap-able arena, `native/hotcache.cpp`) and run "
        "the probe -> packed-reply loop entirely in their own address "
        "space: the hit path shares NO GIL and crosses NO pipe — the "
        "seqlock stamp protocol is address-free, so a frontend reads "
        "the same generation-consistent rows the owner publishes, "
        "torn reads retry and then miss (never serve a mix). Cold "
        "misses cross a bounded pipe to the owner and are answered "
        "from the replica plane, so the staleness SLO is unchanged. "
        "The bench primes the arena, measures the owner's own "
        "1-process packed loop for scaling context, then drives the "
        "same batch shape from every frontend while the owner keeps "
        "priming fresh generations at the publish cadence; the "
        "aggregate comes from the SHARED arena-header per-frontend "
        "counters (`fe_stats`), not wall-clock division, and the row "
        "FAILS on a sub-0.98 hit rate or a frozen (unprimed) table. "
        "On a 1-core CI box the frontends time-share the clock; "
        "`tools/tier1.sh` runs `tools/frontend_smoke.py` which gates "
        "the structural claims regardless of core count: zero torn "
        "reads across a cross-process seqlock fuzz, bit-identical "
        "parity with the owner's dict oracle, staleness-SLO held "
        "through the frontend path, and a real frontend-kill failover "
        "(design in NOTES_r21.md).")
    lines.append("")
    lines.append(
        "Streaming-join rows (r14): `tools/bench_joins.py` drives the "
        "device-native interval-join engine (`flink_tpu/joins/` — dual "
        "keyed slot tables co-partitioned by the keyBy exchange, one "
        "banded segment-intersection program per batch, design in "
        "NOTES_r14.md). `fire_latency_ms` is the EMIT latency: wall "
        "time from an arriving batch to its matches materialized on "
        "the host (the two-input analogue of window fire latency — "
        "every windowed row reports fire percentiles since r14, which "
        "is also the hook ROADMAP item 2's latency gate needs). The "
        "10M-key row forces paged eviction (live rows >> device "
        "budget) and FAILS as vacuous if spill never engages; "
        "`tools/join_smoke.py` gates the same engine bit-identical to "
        "its host-numpy oracle in tier-1.")
    lines.append("")
    lines.append(
        "CEP row (r22): `tools/bench_cep.py` drives the "
        "device-vectorized mesh NFA engine "
        "(`flink_tpu/cep/mesh_engine.py` — per-key computation states "
        "as int32 bitmask columns on the state plane, ONE compiled "
        "gather/scan/scatter advance program per fire, design in "
        "NOTES_r22.md) at the row-5 thrashing shape: a 2-stage "
        "within-window sequence over 10M keys whose live partial set "
        "sits far above the device budget, so the paged tier churns "
        "(asserted — `BENCH_CEP_REQUIRE_SPILL` fails a vacuous run). "
        "The SAME shape runs on the host `CepOperator` NFA — the "
        "bit-identity oracle every CEP gate diffs against — and the "
        "row reports `speedup_vs_host`; `BENCH_CEP_REQUIRE_WIN` makes "
        "a device loss a bench failure, not a footnote. "
        "`fire_latency_ms` is the emit latency from a watermark "
        "advance to matches on the host; `tools/cep_smoke.py` gates "
        "the engine bit-identical (values AND emission order) to the "
        "oracle in tier-1, including a replica-plane matched-pattern "
        "lookup leg.")
    lines.append("")
    lines.append(
        "The shard-loss-recovery row runs `tools/chaos_smoke.py`'s "
        "shard-loss scenario at bench scale: an injected `device.lost` "
        "kills 1 of 4 shards at a batch boundary mid-stream, and the "
        "measured span covers the whole partial failover — survivor "
        "evacuation (live-reshard row lift, dirtiness intact), mesh "
        "rebuild over the remaining devices, restore of ONLY the dead "
        "shard's key groups from their shard-granular checkpoint unit "
        "(flink_tpu/checkpoint/sharded.py), and bounded replay of ONLY "
        "that range's records from the unit's source position. The "
        "tier-1 smoke runs the same scenario smaller and FAILS if the "
        "replay volume exceeds events/shards or the committed output "
        "diverges from the fault-free oracle (NOTES_r13.md).")
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCHMARKS.md")
    with open(out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"# wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
