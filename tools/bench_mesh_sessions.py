"""High-cardinality mesh-sessions benchmark (BASELINE row 5, MESH engine).

Drives ``MeshSessionEngine`` directly at the thrashing shape: 400k ev/s
of event time x 2 s gap ~= 800k concurrently-live sessions against a
512k total device budget (64k slots x 8 shards) over 10M distinct keys —
the live set EXCEEDS the device, so the run exercises the PAGED spill
tier per shard (spill_layout="pages", the port of the single-device
machinery that took row 5 from 9.3k to ~260k ev/s in round 5).

Emits ONE JSON line with events/s and the spill counters (pages
evicted/reloaded, rows split on reload). On CPU the mesh is 8 virtual
host devices (the tests' layout); on TPU the real chips form the mesh.

    BENCH_SKIP_PROBE=1 JAX_PLATFORMS=cpu python tools/bench_mesh_sessions.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# must precede the first jax import: on CPU the mesh needs virtual devices
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

GAP_MS = 2_000
EVENTS_PER_S_OF_EVENTTIME = 400_000
NUM_KEYS = 10_000_000
BUDGET_PER_SHARD = 1 << 16  # x8 shards = the row-5 512k total budget


def run(total: int, mesh, batch: int = 1 << 16):
    import numpy as np

    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate

    eng = MeshSessionEngine(GAP_MS, SumAggregate("v"), mesh,
                            capacity_per_shard=BUDGET_PER_SHARD,
                            max_device_slots=BUDGET_PER_SHARD)
    rng = np.random.default_rng(3)
    produced = 0
    fired = 0
    t0 = time.perf_counter()
    while produced < total:
        b = min(batch, total - produced)
        keys = rng.integers(0, NUM_KEYS, b).astype(np.int64)
        ts = ((produced + np.arange(b, dtype=np.int64)) * 1000
              // EVENTS_PER_S_OF_EVENTTIME)
        eng.process_batch(RecordBatch({
            KEY_ID_FIELD: keys,
            "v": np.ones(b, dtype=np.float32),
            TIMESTAMP_FIELD: ts}))
        produced += b
        fired += sum(len(x) for x in eng.on_watermark(int(ts[-1])))
    fired += sum(len(x) for x in eng.on_watermark(1 << 60))
    dt = time.perf_counter() - t0
    return total / dt, fired, eng.spill_counters()


def main():
    import warnings

    warnings.filterwarnings("ignore")
    from flink_tpu.platform import sync_platform

    sync_platform()
    import jax

    from flink_tpu.parallel.mesh import make_mesh

    P = min(len(jax.devices()), 8)
    mesh = make_mesh(P)
    total = int(os.environ.get("BENCH_MESH_SESSION_RECORDS", 4_000_000))
    run(min(total, 1 << 20), mesh)  # warm: compile the step programs
    eps, fired, counters = run(total, mesh)
    line = {
        "metric": "mesh_sessions_10m_keys_events_per_sec",
        "value": round(eps, 1),
        "unit": "events/s",
        "backend": jax.devices()[0].platform,
        "mesh_shards": P,
        "sessions_fired": fired,
        "spill": counters,
        "shape": (f"400k ev/s event time, 2 s gap, ~800k live sessions "
                  f"vs {P}x{BUDGET_PER_SHARD // 1024}k device slots "
                  f"(paged spill per shard), 10M distinct keys"),
    }
    print(json.dumps(line))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
