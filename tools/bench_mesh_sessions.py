"""High-cardinality mesh-sessions benchmark (BASELINE row 5, MESH engine).

Drives ``MeshSessionEngine`` directly at the thrashing shape: 400k ev/s
of event time x 2 s gap ~= 800k concurrently-live sessions against a
512k total device budget (64k slots x 8 shards) over 10M distinct keys —
the live set EXCEEDS the device, so the run exercises the PAGED spill
tier per shard (spill_layout="pages", lazy-tombstone reloads + threshold
compaction — see flink_tpu/state/paged_spill.py).

The driver is PIPELINED (the bench.py methodology): fires are dispatched
async (``on_watermark(async_ok=True)``) and harvested coalesced while
the host buckets the next batch, and the engine's own dispatch-ahead
overlaps host prep of batch k+1 with the device step of batch k.

The driver is also FIRE-DEADLINE-AWARE (the latency tier,
``BENCH_MESH_FIRE_DEADLINE_MS``, default 25, 0 = legacy whole-batch
path): each ingest batch is split against the deadline using the
measured per-record rate, the watermark advances per split, and landed
fires are harvested between splits — so a fire pops a bounded DELTA of
closing sessions (one fused fire+reset program, the "delta-fire"
PROGRAM_CACHE family) instead of a catch-up pile, and its harvest never
waits out a full batch dispatch. ``fire_latency_ms`` in the JSON is the
executor's definition: wall time from the watermark advance that
dispatched the fire to its results materialized on the host.

The keyBy data plane follows the engine default (``shuffle.mode=device``
— the fused in-program exchange: one flat ``device_put``, segment sort +
``all_to_all`` + scatter in ONE compiled program); set
``BENCH_MESH_SHUFFLE_MODE=host`` to drive the explicit host-bucketing
fallback.

Methodology matches ``bench.py``: one warm pass compiles the step
programs, then BENCH_MESH_REPS (default 3) measured reps; the headline
is the MEDIAN rep, with ``best_events_per_s`` / ``rep_events_per_s`` as
secondary fields. Each rep also reports a host-prep vs device-step vs
harvest wall-time breakdown plus the spill counters. The breakdown is
DERIVED FROM FLIGHT-RECORDER SPANS (``observe.flight_recorder`` +
``observe.export.breakdown_from_kind_totals``), not private driver
timers — the host-prep gate, a captured Perfetto trace and the
dashboard all read the same measurements, so they cannot disagree.
Host-prep attribution is unchanged from the timer era: device work
surfacing inside ``process_batch`` — fence blocks
(``device.fence_wait``) plus inline device interactions
(``device.dispatch``: the fused exchange dispatch, eviction gathers +
D2H, reload puts; the CPU backend executes them inline) — counts as
``device_step_s``, so ``host_prep_s`` / ``host_prep_fraction`` (the
gated number) measure genuine host work: sessionization, slot
resolution, flat staging. ``harvest_s`` now counts ALL D2H
materializations — including ones nested inside device interactions —
so it can overlap ``device_step_s`` (the timer era reported only the
post-loop drain there), and ``device_step_s`` includes the
end-of-input drain fire (the old ``t_fire`` stopped at the loop; the
drain is still separately visible as ``final_drain_ms``).

Regression gates:

- ``BENCH_MESH_AMP_BUDGET`` (a ratio): exit non-zero when the
  page-rewrite amplification ``(rows_split_on_reload + rows_compacted)
  / rows_reloaded`` exceeds it — reload write-amplification cannot
  silently return under ANY counter (the old split-on-reload design
  sat at ~16x; the tombstone design's only rewrites are threshold
  compactions).
- ``BENCH_HOST_PREP_BUDGET`` (a fraction, device mode only): exit
  non-zero when ``host_prep_fraction`` exceeds it — the regression
  class where exchange work silently moves back onto the host.
- ``BENCH_FIRE_P99_BUDGET`` (ms): exit non-zero when the MEDIAN of the
  reps' fire p99 exceeds it — the latency-tier gate (ROADMAP item 1:
  a fire must cost a bounded delta, not a full-window harvest). A run
  that recorded fewer than 10 fires FAILS as vacuous regardless of
  the budget (a shape that fires too rarely measures nothing).

tools/tier1.sh pins all three.

    BENCH_SKIP_PROBE=1 JAX_PLATFORMS=cpu python tools/bench_mesh_sessions.py

Zipf mode (``--zipf`` or ``BENCH_MESH_ZIPF=1``): the same shape with the
key column drawn Zipf(``BENCH_MESH_ZIPF_S``, default 1.1) over the 10M
key space instead of uniform — a handful of keys carry most of the
stream, so the contiguous key-group layout pins one shard at the hot
groups while the others idle. The driver wires the SKEW-ADAPTIVE plane
(``parallel/load.ShardLoadAccountant`` ->
``autoscale/rebalance.RebalancePolicy`` -> ``SkewResponder``): per-batch
load accounting, live key-group MOVES between shards at batch
boundaries (``reassign_key_groups``, P unchanged), and two-stage
HOT-KEY SPLITTING (``register_hot_key``: salted sub-rows pre-aggregated
on their own shards, folded back at fire). The row reports the zipf
throughput, a 1-pass UNIFORM control, and their ratio
(``skew_recovery_fraction``) plus the responder counters; with
``BENCH_SKEW_RECOVERY`` set it FAILS when the ratio drops below the
budget or when the run was vacuous (no live move, nothing salted) —
a green that never rebalanced measures nothing.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# must precede the first jax import: on CPU the mesh needs virtual devices
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

GAP_MS = 2_000
EVENTS_PER_S_OF_EVENTTIME = 400_000
NUM_KEYS = 10_000_000
BUDGET_PER_SHARD = 1 << 16  # x8 shards = the row-5 512k total budget
MAX_PENDING_FIRES = 8


def run(total: int, mesh, batch: int = 1 << 16, zipf: float = 0.0,
        respond: bool = False):
    """One pass; returns (events/s, fired, counters, breakdown,
    fire_latency, skew). ``zipf`` > 0 draws the key column
    Zipf-distributed; ``respond`` wires the skew-adaptive plane
    (load accounting -> live group moves -> hot-key splitting)."""
    import gc
    from collections import deque

    import numpy as np

    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )
    from flink_tpu.observe import flight_recorder as flight
    from flink_tpu.observe.export import breakdown_from_kind_totals
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate

    # the breakdown is derived from flight-recorder spans; a disabled
    # recorder (the trace smoke's A/B baseline) yields a zeroed
    # breakdown — main() refuses to GATE on one (vacuity guard there)
    rec = flight.recorder()
    flight.set_job("bench_mesh_sessions")
    eng = MeshSessionEngine(GAP_MS, SumAggregate("v"), mesh,
                            capacity_per_shard=BUDGET_PER_SHARD,
                            max_device_slots=BUDGET_PER_SHARD,
                            shuffle_mode=os.environ.get(
                                "BENCH_MESH_SHUFFLE_MODE", "device"))
    deadline_s = float(os.environ.get(
        "BENCH_MESH_FIRE_DEADLINE_MS", "25")) / 1000.0
    responder = None
    if respond:
        from flink_tpu.autoscale import RebalancePolicy, SkewResponder
        from flink_tpu.parallel.load import ShardLoadAccountant

        # a 10M-key Zipf tail constantly decrements a small Misra-Gries
        # sketch (estimate >= true - N/(top_k+1)): 64 counters keep the
        # dominant keys' share estimates above the split threshold
        acc = ShardLoadAccountant(eng.P, eng.max_parallelism,
                                  ewma_alpha=0.5,
                                  top_k=int(os.environ.get(
                                      "BENCH_SKEW_TOPK", "64")))
        responder = SkewResponder(
            eng, acc,
            policy=RebalancePolicy(
                imbalance_trigger=float(os.environ.get(
                    "BENCH_SKEW_TRIGGER", "1.25")),
                hysteresis=0.05, cooldown_s=2.0, max_moves=16),
            salts=int(os.environ.get("BENCH_SKEW_SALTS", "16")),
            hot_key_share=0.5, allow_inexact=True)
    rng = np.random.default_rng(3)
    produced = 0
    fired = 0
    pending = deque()  # (PendingFire, watermark-advance start time)
    lat = []  # fire latency: watermark advance -> results on host (ms)
    rate = 0.0  # EMA records/s, sizes the deadline splits
    # the breakdown reads per-kind span aggregates as a DELTA over this
    # pass (clear() resets rings + aggregates; the pass's spans then
    # also ARE the capturable trace — tools/trace_smoke.py reads them)
    rec.clear()

    def harvest(bound=MAX_PENDING_FIRES):
        # coalesced harvest: drain everything whose copy already
        # landed, and enforce a bound so a catch-up burst cannot
        # hoard buffers
        nonlocal fired
        while pending and (pending[0][0].ready() or len(pending) > bound):
            pf, t_wm = pending.popleft()
            fired += len(pf.harvest())
            lat.append((time.perf_counter() - t_wm) * 1e3)

    # the cyclic collector's gen2 pauses (~100 ms over the page-entry
    # object graph) land inside fire spans and dominate p99 — collect
    # the PREVIOUS rep's garbage now, then keep the collector out of
    # the measured loop (numpy buffers are refcounted; re-enabled in
    # the finally below)
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        while produced < total:
            b = min(batch, total - produced)
            if zipf > 0:
                # heavy-tailed keys: a handful of ranks carry most of
                # the stream — the shape the contiguous layout cannot
                # balance and the responder exists to fix
                keys = ((rng.zipf(zipf, b) - 1) % NUM_KEYS).astype(
                    np.int64)
            else:
                keys = rng.integers(0, NUM_KEYS, b).astype(np.int64)
            ts = ((produced + np.arange(b, dtype=np.int64)) * 1000
                  // EVENTS_PER_S_OF_EVENTTIME)
            # fire-deadline-aware micro-batching: ingest splits are sized a
            # small multiple of the deadline (per-dispatch fixed costs —
            # absorb sweep, exchange staging, fences — amortize over the
            # bigger chunk), while the WATERMARK advances in deadline-sized
            # quanta within each split, so every fire pops a bounded DELTA
            # of closing sessions and harvests land between quanta
            if deadline_s <= 0:
                chunk = b
            elif rate <= 0:
                chunk = 16384  # seed until the rate EMA settles
            else:
                # power-of-two split sizes: the rate EMA drifts every step,
                # and a continuously-varying chunk feeds XLA a fresh padded
                # shape per dispatch — pow2 rounding keeps the shape set
                # bounded (0 steady-state compiles, the recompile-smoke
                # contract) so no fire span absorbs a compile
                chunk = 1 << max(int(rate * deadline_s) * 4, 8192).bit_length()
            for a in range(0, b, chunk):
                z = min(a + chunk, b)
                if deadline_s <= 0:
                    quanta = 1
                else:
                    # one watermark quantum per deadline's worth of records
                    per_q = 1 << max(int(rate * deadline_s),
                                     2048).bit_length()
                    quanta = min(max((z - a + per_q - 1) // per_q, 1), 32)
                t1 = time.perf_counter()
                eng.process_batch(RecordBatch({
                    KEY_ID_FIELD: keys[a:z],
                    "v": np.ones(z - a, dtype=np.float32),
                    TIMESTAMP_FIELD: ts[a:z]}))
                t2 = time.perf_counter()
                # dispatch each quantum's fires async; the fused delta-fire
                # program + D2H copies overlap the next quantum's dispatch
                # and the next split's host prep
                for j in range(quanta):
                    w = a + (z - a) * (j + 1) // quanta
                    if w <= a:
                        continue
                    t_wm = time.perf_counter()
                    for pf in eng.on_watermark(int(ts[w - 1]),
                                               async_ok=True):
                        pending.append((pf, t_wm))
                    harvest()
                step_rate = (z - a) / max(t2 - t1, 1e-9)
                rate = step_rate if rate <= 0 else 0.7 * rate + 0.3 * step_rate
                if responder is not None:
                    responder.note_batch(keys[a:z])
            produced += b
            if responder is not None:
                # batch boundary: tick the accountant, maybe move hot
                # groups / register splits (cooldown bounds the churn)
                responder.maybe_respond()
        # drain the steady-state pending fires FIRST: harvested after the
        # shutdown flush below, their samples would carry the whole drain
        # span and pollute the p99 the gate reads
        harvest(bound=0)
        # end-of-input: flush ALL remaining live sessions. This is the
        # shutdown DRAIN, not a steady-state watermark fire — it pops the
        # whole residual state by construction, so it is timed separately
        # (final_drain_ms) and excluded from the fire percentiles the
        # latency gate reads.
        t5 = time.perf_counter()
        for pf in eng.on_watermark(1 << 60, async_ok=True):
            fired += len(pf.harvest())
        t_drain = time.perf_counter() - t5
        dt = time.perf_counter() - t0
        lat.sort()
        # the breakdown comes FROM the recorder's span aggregates (see
        # observe.export.breakdown_from_kind_totals for the attribution
        # contract): host_prep = ingest spans minus the device.dispatch
        # and device.fence_wait spans recorded under them — the same
        # numbers a captured Perfetto trace of this pass shows
        breakdown = breakdown_from_kind_totals(rec.kind_totals(), dt)
        # of which: time inside the NATIVE metadata sweeps (absorb /
        # shard-group / route / pop — 0.0 on the pure-Python plane);
        # pop sweeps land in the fire bucket, so this line can exceed
        # neither bucket alone but attributes the C share explicitly
        breakdown["native_sweep_s"] = round(
            float(getattr(eng.meta, "native_sweep_s", 0.0)), 3)
        from flink_tpu.metrics.core import quantile_sorted

        fire_latency = {
            "p50": round(quantile_sorted(lat, 0.5), 1) if lat else 0.0,
            "p99": round(quantile_sorted(lat, 0.99), 1) if lat else 0.0,
            "max": round(lat[-1], 1) if lat else 0.0,
            "count": len(lat),
            # the end-of-input flush of ALL residual sessions — a shutdown
            # drain, reported but outside the steady-state percentiles
            "final_drain_ms": round(t_drain * 1e3, 1),
        }
        skew = None
        if responder is not None:
            hot = eng.hot_key_stats()
            skew = {
                "rebalances": responder.rebalances,
                "groups_moved": responder.groups_moved,
                "keys_split": responder.keys_split,
                "hot_keys": hot["keys"],
                "salted_records": hot["salted_records"],
                "salted_fires": hot["salted_fires"],
                # measured load imbalance under the LIVE table vs what
                # the contiguous layout would have concentrated
                "imbalance_live": round(responder.accountant.imbalance(
                    eng.key_group_assignment), 3),
                "imbalance_contiguous": round(
                    responder.accountant.imbalance(), 3),
                "assignment_contiguous":
                    eng.key_group_assignment.is_contiguous,
            }
        return (total / dt, fired, eng.spill_counters(), breakdown,
                fire_latency, skew)
    finally:
        gc.enable()


def main_zipf(mesh, P, total, reps_n, native_plane):
    """The skew row: Zipf-keyed stream with the skew-adaptive plane
    live, a 1-pass uniform control as the recovery denominator, and a
    non-vacuous recovery gate (``BENCH_SKEW_RECOVERY``)."""
    import jax

    s = float(os.environ.get("BENCH_MESH_ZIPF_S", "1.1"))
    run(min(total, 1 << 20), mesh, zipf=s, respond=True)  # warm
    uniform_eps, _, _, _, _, _ = run(total, mesh)
    print(f"# uniform control: {uniform_eps:.0f} events/s",
          file=sys.stderr)
    reps = []
    for i in range(reps_n):
        eps, fired, counters, breakdown, fire_lat, skew = run(
            total, mesh, zipf=s, respond=True)
        print(f"# zipf rep {i}: {eps:.0f} events/s, skew={skew}",
              file=sys.stderr)
        reps.append((eps, fired, counters, breakdown, fire_lat, skew))
    by_rate = sorted(reps, key=lambda r: r[0])
    eps, fired, counters, breakdown, fire_lat, skew = \
        by_rate[len(by_rate) // 2]  # median
    recovery = eps / max(uniform_eps, 1e-9)
    line = {
        "metric": "mesh_sessions_zipf_events_per_sec",
        "value": round(eps, 1),
        "unit": "events/s",
        "uniform_events_per_s": round(uniform_eps, 1),
        "skew_recovery_fraction": round(recovery, 3),
        "rep_events_per_s": [round(r[0], 1) for r in reps],
        "backend": jax.devices()[0].platform,
        "mesh_shards": P,
        "native_session_plane": native_plane,
        "zipf_s": s,
        "sessions_fired": fired,
        "spill": counters,
        "skew": skew,
        "fire_latency_ms": fire_lat,
        "shape": (f"Zipf({s}) keys over 10M-key space, 400k ev/s event "
                  f"time, 2 s gap vs {P}x{BUDGET_PER_SHARD // 1024}k "
                  f"device slots (paged spill), skew-adaptive plane "
                  f"live: load-driven key-group moves + hot-key "
                  f"splitting; recovery = zipf/uniform throughput"),
    }
    gate = os.environ.get("BENCH_SKEW_RECOVERY")
    if gate is not None:
        # no vacuous green: a run that never moved a group and never
        # salted a record "recovered" nothing — the plane was idle
        if skew["rebalances"] < 1 or skew["salted_records"] == 0:
            line["error"] = (
                f"skew gate is VACUOUS: rebalances="
                f"{skew['rebalances']}, salted_records="
                f"{skew['salted_records']} — the skew-adaptive plane "
                "never engaged on the Zipf shape")
            print(json.dumps(line))
            sys.exit(1)
        if recovery < float(gate):
            line["error"] = (
                f"skew recovery regressed: zipf/uniform = "
                f"{recovery:.3f} < budget {gate} "
                f"({eps:.0f} vs {uniform_eps:.0f} events/s)")
            print(json.dumps(line))
            sys.exit(1)
    print(json.dumps(line))
    sys.stdout.flush()


def main():
    import warnings

    warnings.filterwarnings("ignore")
    from flink_tpu.platform import sync_platform

    sync_platform()
    import jax

    from flink_tpu.parallel.mesh import make_mesh

    P = min(len(jax.devices()), 8)
    mesh = make_mesh(P)
    from flink_tpu.native import sessions_available

    native_plane = (os.environ.get("FLINK_TPU_NATIVE_SESSIONS") != "0"
                    and sessions_available())
    if os.environ.get("BENCH_REQUIRE_NATIVE") == "1" and not native_plane:
        # no vacuous green: CI asked for the native metadata plane — a
        # silent fallback to pure Python would pass the bench while
        # measuring the wrong data plane entirely
        print(json.dumps({
            "metric": "mesh_sessions_10m_keys_events_per_sec",
            "error": "BENCH_REQUIRE_NATIVE=1 but the native session "
                     "plane is unavailable (compiler missing, build "
                     "failed, or disabled via env)"}))
        sys.exit(1)
    total = int(os.environ.get("BENCH_MESH_SESSION_RECORDS", 4_000_000))
    reps_n = max(int(os.environ.get("BENCH_MESH_REPS", 3)), 1)
    zipf_mode = ("--zipf" in sys.argv
                 or os.environ.get("BENCH_MESH_ZIPF") == "1")
    if zipf_mode:
        return main_zipf(mesh, P, total, reps_n, native_plane)
    run(min(total, 1 << 20), mesh)  # warm: compile the step programs
    reps = []
    for i in range(reps_n):
        eps, fired, counters, breakdown, fire_lat, _ = run(total, mesh)
        print(f"# rep {i}: {eps:.0f} events/s, fire p50/p99 "
              f"{fire_lat['p50']}/{fire_lat['p99']} ms (n="
              f"{fire_lat['count']}), breakdown={breakdown}",
              file=sys.stderr)
        reps.append((eps, fired, counters, breakdown, fire_lat))
    by_rate = sorted(reps, key=lambda r: r[0])
    eps, fired, counters, breakdown, fire_lat = \
        by_rate[len(by_rate) // 2]  # median
    # the latency gate reads the MEDIAN of the reps' p99s (one noisy
    # rep must not decide), mirroring the host-prep gate's median rule
    p99s = sorted(r[4]["p99"] for r in reps)
    median_p99 = p99s[len(p99s) // 2]
    deadline_ms = float(os.environ.get("BENCH_MESH_FIRE_DEADLINE_MS",
                                       "25"))
    mode = os.environ.get("BENCH_MESH_SHUFFLE_MODE", "device")
    line = {
        "metric": "mesh_sessions_10m_keys_events_per_sec",
        "value": round(eps, 1),
        "unit": "events/s",
        "best_events_per_s": round(by_rate[-1][0], 1),
        "rep_events_per_s": [round(r[0], 1) for r in reps],
        "backend": jax.devices()[0].platform,
        "mesh_shards": P,
        "shuffle_mode": mode,
        "native_session_plane": native_plane,
        "sessions_fired": fired,
        "spill": counters,
        "breakdown": breakdown,
        "host_prep_fraction": breakdown["host_prep_fraction"],
        "fire_latency_ms": fire_lat,
        "fire_p99_ms_median": median_p99,
        "fire_p99_ms_reps": p99s,
        "fire_deadline_ms": deadline_ms,
        "shape": (f"400k ev/s event time, 2 s gap, ~800k live sessions "
                  f"vs {P}x{BUDGET_PER_SHARD // 1024}k device slots "
                  f"(paged spill per shard), 10M distinct keys, "
                  f"pipelined driver, {mode}-mode shuffle, "
                  f"{deadline_ms:.0f} ms fire deadline"),
    }
    prep_budget = os.environ.get("BENCH_HOST_PREP_BUDGET")
    if prep_budget is not None and mode == "device":
        from flink_tpu.observe import flight_recorder as flight

        if not flight.enabled():
            # no vacuous green: a disabled recorder zeroes the
            # span-derived breakdown, which would always pass the gate
            line["error"] = (
                "host-prep gate needs the flight recorder: breakdown "
                "is span-derived and FLINK_TPU_FLIGHT_RECORDER=0 "
                "zeroes it")
            print(json.dumps(line))
            sys.exit(1)
        # the device-shuffle contract: host prep is a MINORITY share of
        # wall clock (the exchange runs inside the compiled program) —
        # a regression that moves exchange work back onto the host
        # blows this fraction even when throughput noise hides it
        if breakdown["host_prep_fraction"] > float(prep_budget):
            line["error"] = (
                f"host-prep fraction regressed: "
                f"{breakdown['host_prep_fraction']:.3f} of wall clock "
                f"> budget {prep_budget} in device-shuffle mode")
            print(json.dumps(line))
            sys.exit(1)
    fire_budget = os.environ.get("BENCH_FIRE_P99_BUDGET")
    if fire_budget is not None:
        # vacuity guard FIRST, over EVERY rep (the p99 gate reads the
        # median across reps, so a single under-sampled rep would feed
        # the gate a statistic the guard never validated): a shape that
        # fires too rarely measures nothing — fail loudly
        min_fires = min(r[4]["count"] for r in reps)
        if min_fires < 10:
            line["error"] = (
                f"fire-latency gate is VACUOUS: a rep recorded only "
                f"{min_fires} fires (< 10) — the smoke shape no longer "
                "fires often enough to measure p99")
            print(json.dumps(line))
            sys.exit(1)
        if median_p99 > float(fire_budget):
            line["error"] = (
                f"fire p99 regressed: median of reps "
                f"{median_p99:.1f} ms > budget {fire_budget} ms "
                "(watermark-advance -> results-on-host, the latency "
                "tier's bounded-delta contract)")
            print(json.dumps(line))
            sys.exit(1)
    budget = os.environ.get("BENCH_MESH_AMP_BUDGET")
    if budget is not None:
        # every host-side page REWRITE per row actually reloaded:
        # split-on-reload is structurally 0 in the tombstone design, so
        # the live term is compaction traffic — a regression through
        # either counter trips the same gate
        rewritten = (counters["rows_split_on_reload"]
                     + counters["rows_compacted"])
        ratio = rewritten / max(counters["rows_reloaded"], 1)
        line["rewrite_amplification"] = round(ratio, 4)
        if ratio > float(budget):
            line["error"] = (
                f"reload write-amplification regressed: "
                f"(rows_split_on_reload + rows_compacted)/rows_reloaded"
                f" = {ratio:.3f} > budget {budget}")
            print(json.dumps(line))
            sys.exit(1)
    print(json.dumps(line))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
