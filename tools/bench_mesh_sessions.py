"""High-cardinality mesh-sessions benchmark (BASELINE row 5, MESH engine).

Drives ``MeshSessionEngine`` directly at the thrashing shape: 400k ev/s
of event time x 2 s gap ~= 800k concurrently-live sessions against a
512k total device budget (64k slots x 8 shards) over 10M distinct keys —
the live set EXCEEDS the device, so the run exercises the PAGED spill
tier per shard (spill_layout="pages", lazy-tombstone reloads + threshold
compaction — see flink_tpu/state/paged_spill.py).

The driver is PIPELINED (the bench.py methodology): fires are dispatched
async (``on_watermark(async_ok=True)``) and harvested coalesced while
the host buckets the next batch, and the engine's own dispatch-ahead
overlaps host prep of batch k+1 with the device step of batch k.

The keyBy data plane follows the engine default (``shuffle.mode=device``
— the fused in-program exchange: one flat ``device_put``, segment sort +
``all_to_all`` + scatter in ONE compiled program); set
``BENCH_MESH_SHUFFLE_MODE=host`` to drive the explicit host-bucketing
fallback.

Methodology matches ``bench.py``: one warm pass compiles the step
programs, then BENCH_MESH_REPS (default 3) measured reps; the headline
is the MEDIAN rep, with ``best_events_per_s`` / ``rep_events_per_s`` as
secondary fields. Each rep also reports a host-prep vs device-step vs
harvest wall-time breakdown plus the spill counters. The breakdown
attributes DEVICE work surfacing inside ``process_batch`` — dispatch-
fence blocks plus the engine-timed inline device interactions (the
fused exchange dispatch, eviction gathers + D2H, reload puts; the CPU
backend executes them inline in the dispatch call) — to
``device_step_s``, so ``host_prep_s`` / ``host_prep_fraction`` measure
genuine host work: sessionization, slot resolution, flat staging.

Regression gates:

- ``BENCH_MESH_AMP_BUDGET`` (a ratio): exit non-zero when the
  page-rewrite amplification ``(rows_split_on_reload + rows_compacted)
  / rows_reloaded`` exceeds it — reload write-amplification cannot
  silently return under ANY counter (the old split-on-reload design
  sat at ~16x; the tombstone design's only rewrites are threshold
  compactions).
- ``BENCH_HOST_PREP_BUDGET`` (a fraction, device mode only): exit
  non-zero when ``host_prep_fraction`` exceeds it — the regression
  class where exchange work silently moves back onto the host.

tools/tier1.sh pins both.

    BENCH_SKIP_PROBE=1 JAX_PLATFORMS=cpu python tools/bench_mesh_sessions.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# must precede the first jax import: on CPU the mesh needs virtual devices
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

GAP_MS = 2_000
EVENTS_PER_S_OF_EVENTTIME = 400_000
NUM_KEYS = 10_000_000
BUDGET_PER_SHARD = 1 << 16  # x8 shards = the row-5 512k total budget
MAX_PENDING_FIRES = 8


def run(total: int, mesh, batch: int = 1 << 16):
    """One pass; returns (events/s, fired, counters, breakdown)."""
    from collections import deque

    import numpy as np

    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate

    eng = MeshSessionEngine(GAP_MS, SumAggregate("v"), mesh,
                            capacity_per_shard=BUDGET_PER_SHARD,
                            max_device_slots=BUDGET_PER_SHARD,
                            shuffle_mode=os.environ.get(
                                "BENCH_MESH_SHUFFLE_MODE", "device"))
    rng = np.random.default_rng(3)
    produced = 0
    fired = 0
    pending = deque()
    t_prep = t_fire = t_harvest = 0.0
    t0 = time.perf_counter()
    while produced < total:
        b = min(batch, total - produced)
        keys = rng.integers(0, NUM_KEYS, b).astype(np.int64)
        ts = ((produced + np.arange(b, dtype=np.int64)) * 1000
              // EVENTS_PER_S_OF_EVENTTIME)
        t1 = time.perf_counter()
        eng.process_batch(RecordBatch({
            KEY_ID_FIELD: keys,
            "v": np.ones(b, dtype=np.float32),
            TIMESTAMP_FIELD: ts}))
        t2 = time.perf_counter()
        # dispatch this advance's fires async; the device fire + D2H
        # copy overlap the NEXT batch's host bucketing
        pending.extend(eng.on_watermark(int(ts[-1]), async_ok=True))
        t3 = time.perf_counter()
        # coalesced harvest: drain everything whose copy already landed,
        # and enforce a bound so a catch-up burst cannot hoard buffers
        while pending and (pending[0].ready()
                           or len(pending) > MAX_PENDING_FIRES):
            fired += len(pending.popleft().harvest())
        t4 = time.perf_counter()
        t_prep += t2 - t1
        t_fire += t3 - t2
        t_harvest += t4 - t3
        produced += b
    t5 = time.perf_counter()
    pending.extend(eng.on_watermark(1 << 60, async_ok=True))
    while pending:
        fired += len(pending.popleft().harvest())
    t_harvest += time.perf_counter() - t5
    dt = time.perf_counter() - t0
    # device work surfacing inside process_batch — fence blocks (device
    # work the pipeline could not hide) plus the inline device
    # interactions the engine itself timed (the fused in-program
    # exchange dispatch, eviction gathers + D2H, reload puts; on the
    # CPU backend these execute inline in the dispatch call) — is
    # attributed to DEVICE time, so host_prep measures genuine host
    # work: sessionization, slot resolution, flat staging
    dev_in_prep = (float(getattr(eng, "pipeline_wait_s", 0.0))
                   + float(getattr(eng, "device_inline_s", 0.0)))
    host_prep = max(t_prep - dev_in_prep, 0.0)
    breakdown = {
        # host_prep: sessionization + slot resolution + flat staging
        # (device mode) / bucketing (host mode) + dispatch bookkeeping,
        # EXCLUDING fence blocks and inline device interactions
        "host_prep_s": round(host_prep, 3),
        # of which: time inside the NATIVE metadata sweeps (absorb /
        # shard-group / route / pop — 0.0 on the pure-Python plane);
        # pop sweeps land in the fire bucket, so this line can exceed
        # neither bucket alone but attributes the C share explicitly
        "native_sweep_s": round(
            float(getattr(eng.meta, "native_sweep_s", 0.0)), 3),
        # device_step: fire dispatch + the fire path's synchronous
        # device work (page reloads / cohort evictions for cold fires)
        # + the device share carved out of host prep
        "device_step_s": round(t_fire + dev_in_prep, 3),
        # harvest: materializing fired results on host (coalesced)
        "harvest_s": round(t_harvest, 3),
        "device_in_prep_s": round(dev_in_prep, 3),
        "host_prep_fraction": round(host_prep / dt, 4),
        "total_s": round(dt, 3),
    }
    return total / dt, fired, eng.spill_counters(), breakdown


def main():
    import warnings

    warnings.filterwarnings("ignore")
    from flink_tpu.platform import sync_platform

    sync_platform()
    import jax

    from flink_tpu.parallel.mesh import make_mesh

    P = min(len(jax.devices()), 8)
    mesh = make_mesh(P)
    from flink_tpu.native import sessions_available

    native_plane = (os.environ.get("FLINK_TPU_NATIVE_SESSIONS") != "0"
                    and sessions_available())
    if os.environ.get("BENCH_REQUIRE_NATIVE") == "1" and not native_plane:
        # no vacuous green: CI asked for the native metadata plane — a
        # silent fallback to pure Python would pass the bench while
        # measuring the wrong data plane entirely
        print(json.dumps({
            "metric": "mesh_sessions_10m_keys_events_per_sec",
            "error": "BENCH_REQUIRE_NATIVE=1 but the native session "
                     "plane is unavailable (compiler missing, build "
                     "failed, or disabled via env)"}))
        sys.exit(1)
    total = int(os.environ.get("BENCH_MESH_SESSION_RECORDS", 4_000_000))
    reps_n = max(int(os.environ.get("BENCH_MESH_REPS", 3)), 1)
    run(min(total, 1 << 20), mesh)  # warm: compile the step programs
    reps = []
    for i in range(reps_n):
        eps, fired, counters, breakdown = run(total, mesh)
        print(f"# rep {i}: {eps:.0f} events/s, breakdown={breakdown}",
              file=sys.stderr)
        reps.append((eps, fired, counters, breakdown))
    by_rate = sorted(reps, key=lambda r: r[0])
    eps, fired, counters, breakdown = by_rate[len(by_rate) // 2]  # median
    mode = os.environ.get("BENCH_MESH_SHUFFLE_MODE", "device")
    line = {
        "metric": "mesh_sessions_10m_keys_events_per_sec",
        "value": round(eps, 1),
        "unit": "events/s",
        "best_events_per_s": round(by_rate[-1][0], 1),
        "rep_events_per_s": [round(r[0], 1) for r in reps],
        "backend": jax.devices()[0].platform,
        "mesh_shards": P,
        "shuffle_mode": mode,
        "native_session_plane": native_plane,
        "sessions_fired": fired,
        "spill": counters,
        "breakdown": breakdown,
        "host_prep_fraction": breakdown["host_prep_fraction"],
        "shape": (f"400k ev/s event time, 2 s gap, ~800k live sessions "
                  f"vs {P}x{BUDGET_PER_SHARD // 1024}k device slots "
                  f"(paged spill per shard), 10M distinct keys, "
                  f"pipelined driver, {mode}-mode shuffle"),
    }
    prep_budget = os.environ.get("BENCH_HOST_PREP_BUDGET")
    if prep_budget is not None and mode == "device":
        # the device-shuffle contract: host prep is a MINORITY share of
        # wall clock (the exchange runs inside the compiled program) —
        # a regression that moves exchange work back onto the host
        # blows this fraction even when throughput noise hides it
        if breakdown["host_prep_fraction"] > float(prep_budget):
            line["error"] = (
                f"host-prep fraction regressed: "
                f"{breakdown['host_prep_fraction']:.3f} of wall clock "
                f"> budget {prep_budget} in device-shuffle mode")
            print(json.dumps(line))
            sys.exit(1)
    budget = os.environ.get("BENCH_MESH_AMP_BUDGET")
    if budget is not None:
        # every host-side page REWRITE per row actually reloaded:
        # split-on-reload is structurally 0 in the tombstone design, so
        # the live term is compaction traffic — a regression through
        # either counter trips the same gate
        rewritten = (counters["rows_split_on_reload"]
                     + counters["rows_compacted"])
        ratio = rewritten / max(counters["rows_reloaded"], 1)
        line["rewrite_amplification"] = round(ratio, 4)
        if ratio > float(budget):
            line["error"] = (
                f"reload write-amplification regressed: "
                f"(rows_split_on_reload + rows_compacted)/rows_reloaded"
                f" = {ratio:.3f} > budget {budget}")
            print(json.dumps(line))
            sys.exit(1)
    print(json.dumps(line))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
