"""Autoscale smoke: a deterministic load ramp through the policy +
live-reshard path, for the tier-1 gate.

Drives the mesh session engine (paged spill, forced eviction) through a
low -> high -> low synthetic load ramp while an
:class:`AutoscaleController` ticks a DS2-style policy on a FAKE clock
(signals are derived from the scripted ramp, so every decision is
reproducible). The run FAILS (non-zero exit) if

- the policy never scales 2 -> 4 on the ramp-up or back to 2 on the
  ramp-down (the decision loop went stale), or
- fewer than two LIVE handoffs happened (the rescales took some other
  path), or
- the final output diverges from the fault-free single-device oracle by
  even one window (live migration lost/duplicated state).

    JAX_PLATFORMS=cpu python tools/autoscale_smoke.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# must precede the first jax import: on CPU the mesh needs virtual devices
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

GAP = 100
NUM_KEYS = int(os.environ.get("AUTOSCALE_SMOKE_KEYS", 6000))
#: events per step: low -> high (the ramp) -> low again
PHASES = [1500] * 3 + [6000] * 4 + [1500] * 4


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _steps():
    rng = np.random.default_rng(23)
    out = []
    for s, per_step in enumerate(PHASES):
        keys = rng.integers(0, NUM_KEYS, per_step).astype(np.int64)
        vals = rng.random(per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        out.append((keys, vals, ts, (s - 1) * 80))
    return out


def _keyed(keys, vals, ts):
    from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch

    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: keys, "v": vals},
        timestamps=ts)


def _collect(fired, out):
    from flink_tpu.core.records import KEY_ID_FIELD

    for b in fired:
        for r in b.to_rows():
            out[(r[KEY_ID_FIELD], r["window_start"],
                 r["window_end"])] = r["sum_v"]


def main() -> int:
    from flink_tpu.autoscale.controller import (
        AutoscaleController,
        SignalSample,
    )
    from flink_tpu.autoscale.policy import ScalingPolicy
    from flink_tpu.parallel.mesh import make_mesh
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate
    from flink_tpu.windowing.sessions import SessionWindower

    t0 = time.perf_counter()
    steps = _steps()

    # oracle: fault-free, never rescaled, single device
    expected = {}
    oracle = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)
    for keys, vals, ts, wm in steps:
        oracle.process_batch(_keyed(keys, vals, ts))
        _collect(oracle.on_watermark(wm), expected)
    _collect(oracle.on_watermark(1 << 60), expected)

    engine = MeshSessionEngine(
        GAP, SumAggregate("v"), make_mesh(2),
        capacity_per_shard=1 << 14, max_device_slots=1024)
    clk = FakeClock()
    # signals are scripted off the ramp: busy fraction = load / peak.
    # At 1500 ev/step busy=0.25 -> target 1, clamped to min 2; at 6000
    # busy=1.0 -> target ceil(2 * 1.0 / 0.5) = 4.
    cum = {"records": 0.0, "busy_ms": 0.0}
    controller = AutoscaleController(
        ScalingPolicy(utilization_target=0.5, hysteresis=0.25,
                      cooldown_s=2.0, min_shards=2, max_shards=4,
                      clock=clk),
        sample_fn=lambda: SignalSample(
            records_total=cum["records"],
            busy_ms_total=cum["busy_ms"],
            shard_resident_rows=engine.shard_resident_rows()),
        engine=engine, interval_s=0.0, clock=clk)

    got = {}
    for keys, vals, ts, wm in steps:
        n = len(keys)
        cum["records"] += n
        cum["busy_ms"] += min(n / 6000.0, 1.0) * 1000.0
        clk.t += 1.0
        controller.tick()
        engine.process_batch(_keyed(keys, vals, ts))
        _collect(engine.on_watermark(wm), got)
    _collect(engine.on_watermark(1 << 60), got)

    path = [(e.source, e.target) for e in controller.events]
    handoff_ms = [round(e.handoff_s * 1e3, 2) for e in controller.events
                  if e.mode == "live"]
    row = {
        "bench": "autoscale_smoke",
        "seconds": round(time.perf_counter() - t0, 2),
        "events": int(sum(len(s[0]) for s in steps)),
        "windows": len(expected),
        "path": path,
        "live_handoffs": controller.live_handoffs,
        "handoff_ms": handoff_ms,
        "final_shards": int(engine.P),
        "spill": engine.spill_counters(),
    }
    print(json.dumps(row))

    failures = []
    if (2, 4) not in path:
        failures.append(f"policy never scaled 2 -> 4 on the ramp: {path}")
    if (4, 2) not in path:
        failures.append(f"policy never scaled 4 -> 2 back down: {path}")
    if controller.live_handoffs < 2:
        failures.append(
            f"expected >= 2 live handoffs, got {controller.live_handoffs}")
    if set(got) != set(expected):
        failures.append(
            f"window sets differ: {len(got)} vs {len(expected)}")
    else:
        diverged = sum(
            1 for k in expected
            if abs(got[k] - expected[k]) > max(1e-3,
                                               1e-4 * abs(expected[k])))
        if diverged:
            failures.append(
                f"{diverged} windows diverged from the oracle")
    if failures:
        print("AUTOSCALE SMOKE FAILED: " + "; ".join(failures),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
