"""Micro-bench: async (coalesced) keyed state vs per-op sync execution.

Workload: R rounds; each round issues G independent small GETs + P small
PUTs on disjoint key vectors (the shape a process function with several
states / several logical accesses per batch produces). Sync executes each
op as its own kernel; async queues them into one AsyncExecutionController
drain per round (waves coalesce ops into one gather + one scatter).

Prints one JSON line per mode with ops/s and the speedup.
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

from flink_tpu.state.async_state import (  # noqa: E402
    AsyncExecutionController,
    make_async_view,
)
from flink_tpu.state.keyed_state import (  # noqa: E402
    KeyedStateStore,
    ValueStateDescriptor,
)


def run(rounds=2000, ops_per_round=16, keys_per_op=64, mode="sync"):
    store = KeyedStateStore(1 << 16)
    desc = ValueStateDescriptor("v", np.float64, 0.0)
    sync = store.get_state(desc)
    aec = AsyncExecutionController()
    st = make_async_view(aec, sync)
    # disjoint key vectors per op
    keysets = [np.arange(i * keys_per_op, (i + 1) * keys_per_op,
                         dtype=np.int64)
               for i in range(ops_per_round)]
    vals = np.random.default_rng(0).normal(size=keys_per_op)
    store.slots(np.concatenate(keysets))  # pre-insert: measure access only

    t0 = time.perf_counter()
    sink = 0.0
    for _ in range(rounds):
        if mode == "sync":
            for ks in keysets:
                sync.put(ks, vals)
            for ks in keysets:
                sink += float(sync.get(ks)[0])
        else:
            for ks in keysets:
                st.put(ks, vals)
            futs = [st.get(ks) for ks in keysets]
            aec.drain()
            sink += sum(float(f.value()[0]) for f in futs)
    dt = time.perf_counter() - t0
    n_ops = rounds * ops_per_round * 2
    return {"mode": mode, "ops_per_s": n_ops / dt, "elapsed_s": dt,
            "kernel_calls": aec.stats["kernel_calls"] or n_ops}


def main():
    s = run(mode="sync")
    a = run(mode="async")
    for r in (s, a):
        print(json.dumps({k: round(v, 1) if isinstance(v, float) else v
                          for k, v in r.items()}))
    print(json.dumps({"metric": "async_state_speedup_vs_sync",
                      "value": round(a["ops_per_s"] / s["ops_per_s"], 3),
                      "unit": "x"}))


if __name__ == "__main__":
    main()
