"""Pallas A/B gate (tier-1): the stateplane's first Pallas kernel —
the exchange-rank counting sort — against the XLA one-hot-cumsum it
replaces, bit-for-bit at three levels:

- KERNEL: random (num_dests, length, width) shapes with in-range,
  out-of-range (staging pads) and negative destinations — ranks and
  flattened (dest, rank) scatter positions must be EXACTLY equal.
- PROGRAM: the cached ``exchange-rank`` programs (xla vs pallas keys)
  agree, and occupy DISTINCT cache entries (cache-key honesty — a
  backend swap is a new key, never a silent retrace).
- ENGINE: a device-mode mesh session run under
  ``backend_scope("exchange-rank", "pallas")`` emits bit-identical
  fires IN ORDER vs the default backend — same ranks means same bucket
  positions means same downstream fold order.

On CPU the kernel runs in Pallas interpret mode — that IS the CI
configuration; on TPU the same code path compiles to Mosaic. When the
pallas kernel is unavailable on this host the gate SKIPS LOUDLY and
exits 0 (the migration must not brick hosts without it), printing an
unmistakable marker line for the tier-1 log.

    JAX_PLATFORMS=cpu python tools/pallas_ab_gate.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

SHAPES = int(os.environ.get("PALLAS_AB_SHAPES", 40))
STEPS = 6
BATCH = 4000
NUM_KEYS = 15_000


def _kernel_leg(errs):
    from flink_tpu.stateplane.rank import (
        exchange_rank_flat,
        pallas_rank,
        xla_rank,
    )

    rng = np.random.default_rng(101)
    for i in range(SHAPES):
        D = int(rng.integers(1, 17))
        n = int(rng.integers(1, 600))
        W = int(rng.integers(1, 64))
        d = rng.integers(-2, D + 3, size=n).astype(np.int32)
        pr = np.asarray(pallas_rank(d, D))
        xr = np.asarray(xla_rank(d, D))
        if not (pr == xr).all():
            errs.append(f"kernel: rank diverges at shape {i} "
                        f"(D={D} n={n})")
            return
        pf = np.asarray(exchange_rank_flat(d, D, W, "pallas"))
        xf = np.asarray(exchange_rank_flat(d, D, W, "xla"))
        if not (pf == xf).all():
            errs.append(f"kernel: flat scatter position diverges at "
                        f"shape {i} (D={D} n={n} W={W})")
            return


def _program_leg(errs):
    from flink_tpu.stateplane.rank import build_exchange_rank

    d = np.asarray([5, 0, 2, 0, 9, 5, 5, -1, 0, 3], dtype=np.int32)
    px = build_exchange_rank(8, "xla")
    pp = build_exchange_rank(8, "pallas")
    if px is pp:
        errs.append("program: xla and pallas share one cache entry — "
                    "the backend is missing from the cache key")
    if not (np.asarray(px(d, 4)) == np.asarray(pp(d, 4))).all():
        errs.append("program: cached exchange-rank programs diverge")


def _engine_leg(mesh, errs):
    """Bit-identical fires (emission order included) for a device-mode
    session run across backends — the downstream-fold-order half."""
    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.stateplane import backend_scope
    from flink_tpu.windowing.aggregates import SumAggregate

    def run():
        eng = MeshSessionEngine(gap=100, agg=SumAggregate("v"),
                                mesh=mesh,
                                capacity_per_shard=1 << 14,
                                shuffle_mode="device",
                                max_device_slots=1024)
        rng = np.random.default_rng(71)
        rows = []
        for s in range(STEPS):
            keys = rng.integers(0, NUM_KEYS, BATCH).astype(np.int64)
            vals = rng.integers(0, 1000, BATCH).astype(np.float32)
            ts = np.sort(rng.integers(s * 80, s * 80 + 60,
                                      BATCH)).astype(np.int64)
            eng.process_batch(RecordBatch({
                KEY_ID_FIELD: keys, "v": vals, TIMESTAMP_FIELD: ts}))
            for b in eng.on_watermark((s - 1) * 80):
                for r, t in zip(b.to_rows(),
                                np.asarray(b.timestamps).tolist()):
                    rows.append((t, tuple(sorted(r.items()))))
        return rows

    base = run()
    with backend_scope("exchange-rank", "pallas"):
        swapped = run()
    if not base:
        errs.append("engine: zero fires — vacuous A/B")
    if base != swapped:
        errs.append(f"engine: fires diverge across backends "
                    f"({len(base)} vs {len(swapped)} rows, or "
                    "order/values differ)")
    return len(base)


def main():
    import warnings

    warnings.filterwarnings("ignore")
    import jax

    from flink_tpu.parallel.mesh import make_mesh
    from flink_tpu.stateplane import pallas_available

    t0 = time.perf_counter()
    if not pallas_available():
        print("PALLAS A/B GATE: SKIPPED — pallas kernel unavailable "
              "on this host (no pallas install, or the interpret-mode "
              "probe failed); the exchange-rank backend stays XLA and "
              "the bit-identity claim is NOT verified here",
              file=sys.stderr)
        print(json.dumps({"pallas_ab_gate": "SKIPPED"}))
        return 0
    errs = []
    _kernel_leg(errs)
    _program_leg(errs)
    fires = _engine_leg(make_mesh(min(len(jax.devices()), 8)), errs)
    print(json.dumps({
        "pallas_ab_gate": "ok" if not errs else "FAIL",
        "shapes": SHAPES,
        "engine_fires": fires,
        "seconds": round(time.perf_counter() - t0, 2),
    }))
    for e in errs:
        print(f"FAIL: {e}", file=sys.stderr)
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
