"""Bench: host vs device OVER aggregation engines.

Workload: one operator fed B batches of R rows over K keys, ROWS
n-PRECEDING frames with SUM/AVG/MIN/MAX — the shape where the host
engine's per-key-segment Python loop is the bottleneck and the device
engine's fused scans should win as K grows.

Prints one JSON line per (engine, keys) with rows/s, then a summary
speedup line. Run on the default backend (TPU when the tunnel is up,
else CPU-jax): ``python tools/bench_over.py``.
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

from flink_tpu.core.records import (  # noqa: E402
    KEY_ID_FIELD,
    TIMESTAMP_FIELD,
    RecordBatch,
)


def make_batches(rng, n_batches, rows, keys, ts_step=1, wm=0):
    batches, wms = [], []
    for _ in range(n_batches):
        new_wm = wm + rows * ts_step
        ts = np.sort(rng.integers(wm + 1, new_wm + 1, size=rows))
        batches.append(RecordBatch({
            KEY_ID_FIELD: rng.integers(0, keys, rows).astype(np.int64),
            "x": rng.normal(size=rows),
            TIMESTAMP_FIELD: ts.astype(np.int64)}))
        wms.append(new_wm)
        wm = new_wm
    return batches, wms


def run(engine: str, keys: int, n_batches=20, rows=50_000,
        preceding=16) -> dict:
    from flink_tpu.runtime.over_agg import OverAggOperator
    from flink_tpu.runtime.over_device import DeviceOverAggOperator

    specs = [("SUM", "x", "__s__"), ("AVG", "x", "__a__"),
             ("MIN", "x", "__mn__"), ("MAX", "x", "__mx__")]
    cls = DeviceOverAggOperator if engine == "device" else OverAggOperator
    op = cls("k", specs, mode="ROWS", preceding=preceding)
    op.open(None)
    rng = np.random.default_rng(1)
    # warmup fires (compile) — THREE: the padded kernel size steps up
    # once per-key context fills in (fire 1 has no context), so both
    # compiled shapes must be warm before timing; measured batches
    # follow in event time so none of their rows arrive late
    wb, wwm = make_batches(rng, 3, rows, keys)
    batches, wms = make_batches(rng, n_batches, rows, keys, wm=wwm[-1])
    for b, wm in zip(wb, wwm):
        op.process_batch(b)
        op.process_watermark(wm)

    t0 = time.perf_counter()
    n_out = 0
    for b, wm in zip(batches, wms):
        op.process_batch(b)
        for o in op.process_watermark(wm):
            n_out += len(o)
    dt = time.perf_counter() - t0
    total = n_batches * rows
    assert n_out == total, (n_out, total)
    return {"engine": engine, "keys": keys,
            "rows_per_s": total / dt, "elapsed_s": dt}


def main():
    speedups = {}
    for keys in (100, 2_000, 50_000):
        r_host = run("host", keys)
        r_dev = run("device", keys)
        for r in (r_host, r_dev):
            print(json.dumps({k: round(v, 1)
                              if isinstance(v, float) else v
                              for k, v in r.items()}))
        speedups[keys] = r_dev["rows_per_s"] / r_host["rows_per_s"]
    print(json.dumps({
        "metric": "over_device_speedup_vs_host",
        "value": {str(k): round(v, 3) for k, v in speedups.items()},
        "unit": "x (by key count)"}))


if __name__ == "__main__":
    main()
