"""Trace smoke — the flight recorder's tier-1 gate.

Three claims, all falsifiable, all checked at the mesh-sessions bench
shape (the same driver the perf gates run — ``bench_mesh_sessions.run``
with the recorder's spans as the capture):

1. **Schema**: a captured Chrome/Perfetto trace of a steady-state pass
   is well-formed — every event's name is a registered span kind
   (``observe.KNOWN_SPAN_KINDS``), ``batch.ingest`` spans carry batch
   attribution, fires carry watermarks, and per-shard attribution is
   present (``fire.shard`` events with shard >= 0). A schema drift
   between recorder call sites and exporters fails HERE, not in a
   debugging session three PRs later.
2. **Steady state is quiet**: the measured (post-warm) pass records
   ZERO ``xla.compile`` events — the recorder's compile correlation
   agrees with the recompile-sentinel contract.
3. **Overhead**: the recorder must cost at most
   ``TRACE_SMOKE_OVERHEAD_BUDGET`` (default 0.03 = 3%) of the pass's
   wall clock. Gated on a DIRECT MEASUREMENT: the per-record recorder
   cost is microbenched live in this process, multiplied by the number
   of records the measured pass actually wrote, divided by that pass's
   wall time — microsecond-precise, and it catches both regression
   classes (a slower ``span()``/``instant()`` shows in the microbench;
   an instrumentation point multiplying onto a per-record path shows
   in the count). The A/B throughput ratio (``TRACE_SMOKE_REPS``
   alternating recorder-on/off pairs, median of paired ratios) is
   reported alongside and sanity-bounded at 5x the budget — on the
   1-core CI box scheduler noise is ~±10% between reps, an order
   above the ~1% true overhead, so a tight A/B gate would flake on
   noise rather than regressions (observed: three consecutive runs of
   a 3% median-ratio gate read -3.9%, +3.2%, +0.2%).

    JAX_PLATFORMS=cpu python tools/trace_smoke.py

Env: TRACE_SMOKE_RECORDS (default 1<<20), TRACE_SMOKE_REPS,
TRACE_SMOKE_OVERHEAD_BUDGET, TRACE_SMOKE_OUT (optional path to keep
the captured trace).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def main() -> int:
    import warnings

    warnings.filterwarnings("ignore")
    import jax

    from flink_tpu.observe import KNOWN_SPAN_KINDS, install_probes
    from flink_tpu.observe import flight_recorder as flight
    from flink_tpu.observe.export import (
        chrome_trace,
        validate_trace_schema,
    )
    from flink_tpu.parallel.mesh import make_mesh
    from tools.bench_mesh_sessions import run

    if not flight.enabled():
        print(json.dumps({"metric": "trace_smoke", "error":
                          "FLINK_TPU_FLIGHT_RECORDER=0 — the smoke "
                          "exists to gate the always-on recorder"}))
        return 1
    install_probes()
    records = int(os.environ.get("TRACE_SMOKE_RECORDS", 1 << 20))
    reps = max(int(os.environ.get("TRACE_SMOKE_REPS", 5)), 1)
    budget = float(os.environ.get("TRACE_SMOKE_OVERHEAD_BUDGET", 0.03))
    mesh = make_mesh(min(len(jax.devices()), 8))
    rec = flight.recorder()

    run(min(records, 1 << 20), mesh)  # warm: compile everything once
    on_eps, off_eps = [], []
    for i in range(reps):
        # paired A/B with alternating order: adjacent runs see the
        # same box state, so the per-pair ratio cancels slow drift,
        # and alternating cancels within-pair ordering bias
        if i % 2 == 0:
            with flight.disabled():
                off_eps.append(run(records, mesh)[0])
            on_eps.append(run(records, mesh)[0])
        else:
            on_eps.append(run(records, mesh)[0])
            with flight.disabled():
                off_eps.append(run(records, mesh)[0])
    # throughput of the pass whose rings the capture + overhead math
    # below describe (the LAST recorder-on run)
    capture_eps = on_eps[-1]
    if reps % 2 == 0:
        # an even rep count ends on an OFF pass — the capture below
        # must come from a recorder-ON one. UNSCORED for the A/B
        # ratios (replacing a measured sample would break the
        # adjacent-pair premise), but its throughput still anchors the
        # overhead math: rings and wall time must come from ONE pass
        capture_eps = run(records, mesh)[0]
    # the LAST pass ran recorder-on: its rings are the captured trace
    # and its per-kind aggregates are the steady-state evidence
    totals = rec.kind_totals()
    trace = chrome_trace(rec.snapshot(), anchor=rec.anchor)
    out_path = os.environ.get("TRACE_SMOKE_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(trace, f)

    line = {
        "metric": "trace_smoke",
        "records": records,
        "reps": reps,
        "recorder_on_events_per_s": [round(x, 1) for x in on_eps],
        "recorder_off_events_per_s": [round(x, 1) for x in off_eps],
        "trace_events": len(trace["traceEvents"]),
        "span_kinds": sorted(totals),
        "dropped_oldest": rec.dropped(),
    }

    # --- 1. schema -------------------------------------------------------
    problems = validate_trace_schema(trace, KNOWN_SPAN_KINDS)
    data_events = [e for e in trace["traceEvents"] if e["ph"] != "M"]
    if len(data_events) < 50:
        problems.append(
            f"vacuous capture: only {len(data_events)} events — the "
            "bench shape no longer exercises the span plane")
    lifecycle = {"batch.ingest", "fire.dispatch", "fire.harvest",
                 "device.dispatch"}
    missing = lifecycle - set(totals)
    if missing:
        problems.append(f"lifecycle span kinds absent from the "
                        f"capture: {sorted(missing)}")
    if not any(e.get("args", {}).get("shard", -1) >= 0
               for e in data_events):
        problems.append("no per-shard attribution in the capture "
                        "(no event carries shard >= 0)")
    if problems:
        line["error"] = "; ".join(problems)
        print(json.dumps(line))
        return 1

    # --- 2. quiet steady state ------------------------------------------
    compiles = int(totals.get("xla.compile", {}).get("count", 0))
    line["steady_state_compiles"] = compiles
    if compiles:
        line["error"] = (
            f"{compiles} XLA compile event(s) recorded in the measured "
            "pass — the steady state is recompiling (and every such "
            "compile now lands inside a visible span in the trace)")
        print(json.dumps(line))
        return 1

    # --- 3. overhead -----------------------------------------------------
    # (a) the DIRECT measurement: live per-record recorder cost x the
    # measured pass's actual record count / its wall time
    import time as _time

    # count the measured pass's records FIRST — the microbench below
    # writes its own 20k records into the same rings
    records_written = sum(r.cursor for r in rec._iter_rings())
    n_bench = 20000
    t0 = _time.perf_counter()
    for _ in range(n_bench):
        with flight.span("emit"):
            pass
    cost_s = (_time.perf_counter() - t0) / n_bench
    wall_on = records / capture_eps if capture_eps > 0 else 0.0
    overhead = (records_written * cost_s / wall_on) if wall_on else 0.0
    line["recorder_records"] = records_written
    line["span_cost_us"] = round(cost_s * 1e6, 2)
    line["overhead_fraction"] = round(overhead, 4)
    line["overhead_budget"] = budget
    if overhead > budget:
        line["error"] = (
            f"recorder overhead regressed: {records_written} records x "
            f"{cost_s * 1e6:.1f} us = {overhead * 100:.2f}% of the "
            f"pass's wall clock > {budget * 100:.0f}% budget — the "
            "always-on span plane must stay cheap (preallocated "
            "rings, no hot-path allocation)")
        print(json.dumps(line))
        return 1
    # (b) the A/B sanity bound: paired ratios (adjacent in time, order
    # alternating) cancel box drift; the bound is LOOSE (5x budget)
    # because scheduler noise here is an order above the true overhead
    ratios = [on / off for on, off in zip(on_eps, off_eps) if off > 0]
    ab_overhead = 1.0 - _median(ratios) if ratios else 0.0
    line["ab_overhead_fraction"] = round(ab_overhead, 4)
    line["pair_ratios"] = [round(r, 4) for r in ratios]
    if ab_overhead > 5 * budget:
        line["error"] = (
            f"recorder-on throughput collapsed: median paired ON/OFF "
            f"ratio {_median(ratios):.3f} = {ab_overhead * 100:.0f}% "
            f"loss > the {5 * budget * 100:.0f}% sanity bound — a "
            "gross regression the per-record cost model cannot see "
            "(lock contention? allocation storm?)")
        print(json.dumps(line))
        return 1
    # --- 4. serving capture: lookups attribute to (job, generation) ------
    # a small tenancy job under lookup load must leave serving spans in
    # the rings with the TENANT named and the replica generation in the
    # batch field (serving.lookup) plus boundary publishes
    # (serving.replica_publish) — the correlation the Perfetto view
    # needs to explain a slow lookup by what the replica was doing
    rec.clear()
    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.connectors.sources import DataGenSource
    from flink_tpu.core.config import Configuration
    from flink_tpu.datastream.environment import (
        StreamExecutionEnvironment,
    )
    from flink_tpu.runtime.watermarks import WatermarkStrategy
    from flink_tpu.tenancy.session_cluster import SessionCluster
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 4096,
        "parallelism.default": 4,
    }))
    (env.add_source(
        DataGenSource(total_records=32768, num_keys=128,
                      events_per_second_of_eventtime=50_000, seed=7),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("key")
        .window(TumblingEventTimeWindows.of(60_000))
        .sum("value").sink_to(CollectSink()))
    cluster = SessionCluster(quantum_records=4096)
    cluster.submit(env, "trace-job")
    rounds = 0
    while cluster.step_round() and rounds < 8:
        rounds += 1
        try:
            # fresh keys each round: misses exercise the worker flush
            # (the serving.lookup span); repeats exercise the cache
            cluster.lookup_batch(
                "trace-job", "window_agg(SumAggregate)",
                list(range(16)) + list(range(rounds * 64,
                                             rounds * 64 + 32)))
        except RuntimeError:
            pass  # pre-first-publish rounds
    cluster.run(timeout_s=120)
    cluster.serving.shutdown_workers()
    spans = rec.snapshot()
    lookups_attr = [s for s in spans if s.kind == "serving.lookup"
                    and s.job == "trace-job" and s.batch_id >= 1]
    publishes = [s for s in spans
                 if s.kind == "serving.replica_publish"]
    line["serving_lookup_spans"] = len(lookups_attr)
    line["serving_publish_spans"] = len(publishes)
    problems = []
    if not publishes:
        problems.append(
            "no serving.replica_publish span captured — boundary "
            "publishes are invisible to the trace")
    if not lookups_attr:
        problems.append(
            "no serving.lookup span attributed to (job, generation) — "
            "a slow lookup cannot be correlated to its tenant and "
            "replica generation in the Perfetto view")
    if problems:
        line["error"] = "; ".join(problems)
        print(json.dumps(line))
        return 1

    # --- 5. two-level exchange: stage-1 vs stage-2 attribution -----------
    # a short pass with the pod (2 x P/2) topology armed must attribute
    # the ICI route and the DCN hop as DISTINCT span kinds with real
    # time in each — the pod-scale perf story is only debuggable if
    # the trace says which level a slow exchange spent its time in
    rec.clear()
    import numpy as np

    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )
    from flink_tpu.parallel.mesh import HostTopology
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate

    P = int(mesh.devices.size)
    if P < 2 or P % 2:
        # a 1-device or odd mesh cannot factor into (2, P/2) — the
        # phase needs the pod topology to exist (recompile_smoke's
        # two-level phase skips the same way)
        line["exchange_stage_phase"] = f"skipped ({P} devices)"
        print(json.dumps(line))
        return 0
    eng = MeshSessionEngine(
        16_000, SumAggregate("v"), mesh,
        capacity_per_shard=1 << 14,
        host_topology=HostTopology(2, P // 2))
    rng = np.random.default_rng(5)
    t = 0
    for _ in range(6):
        n = 4096
        ks = rng.integers(0, 20_000, n).astype(np.int64)
        ts = t + np.arange(n, dtype=np.int64) // 4
        eng.process_batch(RecordBatch({
            KEY_ID_FIELD: ks, "v": np.ones(n, dtype=np.float32),
            TIMESTAMP_FIELD: ts}))
        t = int(ts[-1]) + 1
        eng.on_watermark(t - 16_000)
    totals2 = rec.kind_totals()
    s1 = totals2.get("exchange.stage1", {})
    s2 = totals2.get("exchange.stage2", {})
    line["exchange_stage1_spans"] = int(s1.get("count", 0))
    line["exchange_stage2_spans"] = int(s2.get("count", 0))
    line["exchange_stage1_ms"] = round(s1.get("total_s", 0.0) * 1e3, 2)
    line["exchange_stage2_ms"] = round(s2.get("total_s", 0.0) * 1e3, 2)
    problems = []
    if not s1.get("count") or not s2.get("count"):
        problems.append(
            "two-level exchange stages missing from the capture "
            f"(stage1={s1.get('count', 0)}, "
            f"stage2={s2.get('count', 0)} spans) — ICI vs DCN time "
            "cannot be attributed")
    elif not (s1.get("total_s", 0) > 0 and s2.get("total_s", 0) > 0):
        problems.append("two-level exchange stages carry no time")
    if problems:
        line["error"] = "; ".join(problems)
        print(json.dumps(line))
        return 1
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
