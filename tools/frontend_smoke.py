"""Multi-process serving-tier smoke: 2 shm frontends under live load
(tier-1).

The executable form of the frontend-tier acceptance criteria on a box
of ANY core count — structural claims, not throughput (the throughput
row is tools/bench_serving_mp.py, recorded in BENCHMARKS.md):

1. **Seqlock fuzz phase** — an owner process writes generation after
   generation into a shm-backed hot cache while TWO frontend reader
   processes attach and probe the SAME arena continuously. Every hit
   is verified against the generation-deterministic value scheme
   ``v == g * 1e6 + key`` (both columns written under one seqlock
   stamp cycle). The run FAILS on:
   - ANY torn read surfacing (an inconsistent ``(g, v)`` pair),
   - zero reader hits, or readers observing only one generation
     (vacuity: the writer must really mutate under the probes).
2. **Serving parity phase** — a session cluster ingests a real job
   with the shm serving tier armed (``serving_shm_dir``) while client
   threads hammer ``FrontendPool.lookup_batch`` (hits answered inside
   the frontend processes, misses crossing to the owner's replica
   path). The run FAILS on:
   - owner/frontend parity divergence (a sampled frontend batch must
     equal the owner's own ``lookup_batch`` — repeated mismatch only,
     a publish landing between the two calls moves one boundary),
   - replica staleness p99 over ``FRONTEND_SMOKE_STALENESS_BUDGET_MS``
     (default 2000 — the frontends must not starve the publish loop),
   - zero frontend hits (vacuity: the shm hit path must actually
     serve — hit rate > 0),
   - any client error, or both frontends dying.

    JAX_PLATFORMS=cpu python tools/frontend_smoke.py
    FRONTEND_SMOKE_RECORDS=... to scale the ingest phase.
"""

import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

RECORDS = int(os.environ.get("FRONTEND_SMOKE_RECORDS", 60_000))
KEYS = int(os.environ.get("FRONTEND_SMOKE_KEYS", 2048))
CLIENTS = int(os.environ.get("FRONTEND_SMOKE_CLIENTS", 4))
FRONTENDS = int(os.environ.get("FRONTEND_SMOKE_FRONTENDS", 2))
FUZZ_SECONDS = float(os.environ.get("FRONTEND_SMOKE_FUZZ_S", 2.0))
STALENESS_BUDGET_MS = float(os.environ.get(
    "FRONTEND_SMOKE_STALENESS_BUDGET_MS", 2000))
LOOKUP_BATCH = int(os.environ.get("FRONTEND_SMOKE_LOOKUP_BATCH", 128))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Reader process body for the fuzz phase (same oracle as
# tests/test_serving_frontend.py): probe continuously, verify every
# hit's (g, v) pair against the formula of exactly one generation.
_READER_SRC = r"""
import json, os, sys, time
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from flink_tpu.tenancy.hot_cache_native import FrontendCacheClient

shm_dir, fe_id, seconds = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
client = FrontendCacheClient(shm_dir, frontend_id=fe_id)
keys = np.arange(128, dtype=np.int64)
probes = hits = bad = 0
gens = set()
deadline = time.monotonic() + seconds
# under heavy box load the probe window can land after the writer's
# first generations — extend (bounded) until live mutation was seen
hard = deadline + 20.0
while (time.monotonic() < deadline
       or (len(gens) < 2 and time.monotonic() < hard)):
    n, probe, misses = client.probe("fuzz", "op", keys)
    probes += len(keys)
    hits += n
    if probe is None:
        continue
    for i in range(len(keys)):
        if not probe.hit[i]:
            continue
        row = probe.materialize(i)[0]
        if row["v"] != row["g"] * 1_000_000.0 + float(keys[i]):
            bad += 1
        gens.add(row["g"])
client.close()
print(json.dumps({"probes": probes, "hits": hits, "bad": bad,
                  "n_gens": len(gens)}))
"""


def fuzz_phase(tmp: str) -> bool:
    """Owner writes live generations; two attached reader processes
    must see zero torn rows. Returns ok."""
    from flink_tpu.tenancy.hot_cache import make_hot_row_cache

    cache = make_hot_row_cache(max_entries=1 << 12,
                               shm_dir=os.path.join(tmp, "fuzz-shm"))
    ok = True
    try:
        keys = list(range(128))

        def write_gen(gen):
            cache.put_many(
                "fuzz", "op", keys, gen,
                [{0: {"g": float(gen),
                      "v": gen * 1_000_000.0 + float(k)}}
                 for k in keys])

        write_gen(1)
        env = dict(os.environ)
        env["PYTHONPATH"] = (_REPO + os.pathsep
                             + env.get("PYTHONPATH", ""))
        readers = [subprocess.Popen(
            [sys.executable, "-c", _READER_SRC, cache.shm_dir,
             str(fe), str(FUZZ_SECONDS)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env=env, text=True) for fe in (1, 2)]
        # write while the READERS are alive (generous hang backstop,
        # not a tight wall budget: a loaded box can spend longer than
        # FUZZ_SECONDS just booting the reader interpreters, and a
        # writer that stops early flakes the multi-generation guard)
        gen = 1
        deadline = time.monotonic() + 60.0
        while (any(r.poll() is None for r in readers)
               and time.monotonic() < deadline):
            gen += 1
            write_gen(gen)
        reports = []
        for r in readers:
            out, err = r.communicate(timeout=60)
            if r.returncode != 0:
                print(f"FAIL: fuzz reader died: {err[-500:]}")
                return False
            reports.append(json.loads(out))
        torn = sum(rep["bad"] for rep in reports)
        hits = sum(rep["hits"] for rep in reports)
        if torn:
            print(f"FAIL: {torn} torn reads surfaced across "
                  f"{hits} hits (seqlock protocol broken over shm)")
            ok = False
        if hits == 0:
            print("FAIL: fuzz readers never hit — vacuous fuzz")
            ok = False
        if not any(rep["n_gens"] > 1 for rep in reports):
            print(f"FAIL: readers saw one generation while the owner "
                  f"wrote {gen} — the probes never overlapped live "
                  "priming (vacuous fuzz)")
            ok = False
        print(f"frontend smoke fuzz: generations={gen} hits={hits} "
              f"torn_surfaced={torn} reader_gens="
              f"{[rep['n_gens'] for rep in reports]}")
    finally:
        cache.close()
    return ok


def serving_phase(tmp: str) -> bool:
    """Real ingest + 2-frontend lookup load: parity, staleness,
    vacuity. Returns ok."""
    import warnings

    warnings.filterwarnings("ignore")
    import numpy as np

    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.connectors.sources import DataGenSource
    from flink_tpu.core.config import Configuration
    from flink_tpu.datastream.environment import (
        StreamExecutionEnvironment,
    )
    from flink_tpu.metrics.core import quantile_sorted
    from flink_tpu.runtime.watermarks import WatermarkStrategy
    from flink_tpu.tenancy.frontend import FrontendPool
    from flink_tpu.tenancy.session_cluster import SessionCluster
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 4096,
        "parallelism.default": 4,
        "serving.replica": True,
        "serving.replica.publish-interval-ms": 25,
    }))
    sink = CollectSink()
    (env.add_source(
        DataGenSource(total_records=RECORDS, num_keys=KEYS,
                      events_per_second_of_eventtime=50_000, seed=13),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("key")
        .window(TumblingEventTimeWindows.of(60_000))
        .sum("value").sink_to(sink))

    cluster = SessionCluster(
        quantum_records=8192,
        serving_shm_dir=os.path.join(tmp, "serving-shm"))
    cluster.submit(env, "job-1")
    operator = "window_agg(SumAggregate)"
    pool = FrontendPool(cluster.serving, n_frontends=FRONTENDS)
    stop = threading.Event()
    errors = []
    parity = {"checked": 0, "diverged": 0}
    staleness = []

    def sampler():
        while not stop.is_set():
            staleness.append(cluster.serving.replica_staleness_ms())
            time.sleep(0.01)

    def client(i):
        rng = np.random.default_rng(500 + i)
        while not stop.is_set():
            ks = rng.integers(0, KEYS, LOOKUP_BATCH).tolist()
            try:
                got = pool.lookup_batch("job-1", operator, ks)
                if i == 0 and parity["checked"] < 8:
                    # owner/frontend parity: same tables + same miss
                    # path must agree; a publish between the two calls
                    # moves one boundary, so only REPEATED mismatch
                    # counts as divergence
                    for _ in range(5):
                        if got == cluster.lookup_batch(
                                "job-1", operator, ks):
                            break
                        got = pool.lookup_batch("job-1", operator, ks)
                    else:
                        parity["diverged"] += 1
                    parity["checked"] += 1
            except (RuntimeError, TimeoutError) as e:
                msg = str(e)
                if ("is not serving" in msg
                        or "already terminated" in msg
                        or "shut down" in msg
                        or "FrontendPool is closed" in msg):
                    return  # job finished: lookups drain off
                errors.append(f"client {i}: {e!r}")
                return
            time.sleep(0.005)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(CLIENTS)]
    threads.append(threading.Thread(target=sampler, daemon=True))
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    try:
        cluster.run(timeout_s=600)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        fe_rows = cluster.serving.hot_cache.fe_stats(FRONTENDS)
        live = len(pool.live_frontends())
        pool.close()
        cluster.serving.hot_cache.close()
    elapsed = time.perf_counter() - t0

    ok = True
    if errors:
        print(f"FAIL: {errors[:3]}")
        ok = False
    if parity["diverged"]:
        print(f"FAIL: {parity['diverged']}/{parity['checked']} "
              "sampled batches diverged between the frontend and the "
              "owner lookup path")
        ok = False
    if parity["checked"] == 0:
        print("FAIL: zero parity samples — vacuous parity gate")
        ok = False
    fe_hits = sum(r["hits"] for r in fe_rows)
    fe_probes = sum(r["probes"] for r in fe_rows)
    fe_crossings = sum(r["miss_crossings"] for r in fe_rows)
    if fe_hits == 0:
        print("FAIL: frontends never served a shm hit — the "
              "multi-process hit path is vacuously off (probes="
              f"{fe_probes})")
        ok = False
    if live == 0:
        print("FAIL: every frontend died during the run")
        ok = False
    staleness_p99 = quantile_sorted(sorted(staleness), 0.99) \
        if staleness else 0.0
    if STALENESS_BUDGET_MS and staleness_p99 > STALENESS_BUDGET_MS:
        print(f"FAIL: replica staleness p99 {staleness_p99:.0f} ms "
              f"over the {STALENESS_BUDGET_MS:.0f} ms budget — the "
              "frontend tier is starving the publish loop")
        ok = False
    if len(sink.result()) == 0:
        print("FAIL: job produced no output")
        ok = False
    print(f"frontend smoke serving: frontends={FRONTENDS} "
          f"live_at_end={live} probes={fe_probes} hits={fe_hits} "
          f"hit_rate={fe_hits / fe_probes if fe_probes else 0.0:.3f} "
          f"miss_crossings={fe_crossings} "
          f"parity_checked={parity['checked']} "
          f"diverged={parity['diverged']} "
          f"staleness_p99={staleness_p99:.1f}ms "
          f"elapsed={elapsed:.1f}s => {'OK' if ok else 'FAIL'}")
    return ok


def main():
    import tempfile

    from flink_tpu.native import hotcache_available

    if not hotcache_available():
        print("FRONTEND SMOKE: native hotcache unavailable — the "
              "multi-process tier cannot exist here")
        return 1
    with tempfile.TemporaryDirectory(prefix="frontend_smoke_") as tmp:
        ok = fuzz_phase(tmp)
        ok = serving_phase(tmp) and ok
    print(f"frontend smoke => {'OK' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
