"""Skew smoke: the skew-adaptive data plane's tier-1 gate.

Drives the mesh session engine through a skewed stream (one key
carrying ~40% of all records) with the :class:`SkewResponder` live,
next to a uniform control run of the same shape, and pins BOTH halves
of the story: the plane must actually engage, and engaging must be
invisible in the output. The run FAILS (non-zero exit) if

- the responder never moved a key group live (``rebalances < 1``,
  ``groups_moved < 1``, or the assignment stayed contiguous), or
- the dominant key was never split (``keys_split < 1``, or zero
  salted records / salted fires — two-stage aggregation never
  engaged: a vacuous green), or
- the applied moves did not improve the accountant's measured
  imbalance vs the contiguous layout, or
- the skewed run's output diverges from the fault-free single-device
  oracle by even one window (integer-valued float32 values keep the
  salted sum fold exact, so the comparison is bit-identity, the part
  the throughput bench does not check), or
- skewed throughput fell below ``BENCH_SKEW_RECOVERY`` (default 0.7)
  of the uniform control — the regression class where the responder
  thrashes and makes skew WORSE than doing nothing.

    JAX_PLATFORMS=cpu python tools/skew_smoke.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# must precede the first jax import: on CPU the mesh needs virtual devices
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

GAP = 100
HOT = 7
NUM_KEYS = 20_000
N_STEPS = 8
TOTAL = int(os.environ.get("SKEW_SMOKE_RECORDS", 1 << 18))
RECOVERY_BUDGET = float(os.environ.get("BENCH_SKEW_RECOVERY", "0.7"))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _steps(hot_frac):
    rng = np.random.default_rng(47)
    per_step = max(2_000, TOTAL // N_STEPS)
    out = []
    for s in range(N_STEPS):
        keys = rng.integers(0, NUM_KEYS, per_step).astype(np.int64)
        if hot_frac:
            keys[rng.random(per_step) < hot_frac] = HOT
        # integer-valued float32: salted sum folds stay exact, so the
        # oracle comparison below can demand bit-identity
        vals = rng.integers(1, 6, per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        out.append((keys, vals, ts, (s - 1) * 80))
    return out


def _keyed(keys, vals, ts):
    from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch

    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: keys, "v": vals}, timestamps=ts)


def _collect(fired, out):
    from flink_tpu.core.records import KEY_ID_FIELD

    for b in fired:
        for r in b.to_rows():
            out[(r[KEY_ID_FIELD], r["window_start"],
                 r["window_end"])] = r["sum_v"]


def _engine():
    from flink_tpu.parallel.mesh import make_mesh
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate

    # paged layout (required for hot-key splitting) with a slot budget
    # small enough that the skewed run genuinely evicts
    return MeshSessionEngine(
        GAP, SumAggregate("v"), make_mesh(4),
        capacity_per_shard=1 << 15, max_device_slots=4096)


def _run(steps, responder_factory=None):
    """One timed pass; returns (outputs, events_per_s, responder)."""
    engine = _engine()
    responder = responder_factory(engine) if responder_factory else None
    got = {}
    t0 = time.perf_counter()
    for keys, vals, ts, wm in steps:
        if responder is not None:
            responder.clock.t += 1.0
            responder.note_batch(keys)
        engine.process_batch(_keyed(keys, vals, ts))
        _collect(engine.on_watermark(wm), got)
        if responder is not None:
            responder.maybe_respond()
    _collect(engine.on_watermark(1 << 60), got)
    dt = time.perf_counter() - t0
    events = sum(len(s[0]) for s in steps)
    return got, events / dt, engine, responder


def main() -> int:
    from flink_tpu.autoscale.rebalance import RebalancePolicy, SkewResponder
    from flink_tpu.parallel.load import ShardLoadAccountant
    from flink_tpu.state.keygroups import KeyGroupAssignment
    from flink_tpu.windowing.aggregates import SumAggregate
    from flink_tpu.windowing.sessions import SessionWindower

    skewed = _steps(hot_frac=0.4)
    uniform = _steps(hot_frac=0.0)

    # oracle: fault-free, never rebalanced, never salted, single device
    expected = {}
    oracle = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 16)
    for keys, vals, ts, wm in skewed:
        oracle.process_batch(_keyed(keys, vals, ts))
        _collect(oracle.on_watermark(wm), expected)
    _collect(oracle.on_watermark(1 << 60), expected)

    # uniform control FIRST: it warms the per-shape XLA cache, so the
    # skewed pass is not charged for shared compiles
    _, uniform_eps, _, _ = _run(uniform)

    def _responder(engine):
        clk = FakeClock()
        acc = ShardLoadAccountant(engine.P, engine.max_parallelism,
                                  ewma_alpha=0.5, top_k=32, clock=clk)
        responder = SkewResponder(
            engine, acc,
            policy=RebalancePolicy(imbalance_trigger=1.3, hysteresis=0.02,
                                   cooldown_s=0.0, clock=clk),
            salts=8, hot_key_share=0.5, allow_inexact=True)
        responder.clock = clk  # the smoke advances time by hand
        return responder

    got, skew_eps, engine, responder = _run(skewed, _responder)
    recovery = skew_eps / uniform_eps if uniform_eps else 0.0

    acc = responder.accountant
    assignment = engine.key_group_assignment
    imb_live = acc.imbalance(assignment)
    imb_contig = acc.imbalance(
        KeyGroupAssignment.contiguous(engine.P, engine.max_parallelism))
    stats = engine.hot_key_stats()
    row = {
        "bench": "skew_smoke",
        "events": int(sum(len(s[0]) for s in skewed)),
        "windows": len(expected),
        "uniform_events_per_s": round(uniform_eps, 1),
        "skew_events_per_s": round(skew_eps, 1),
        "recovery": round(recovery, 3),
        "rebalances": responder.rebalances,
        "groups_moved": responder.groups_moved,
        "keys_split": responder.keys_split,
        "salted_records": stats["salted_records"],
        "salted_fires": stats["salted_fires"],
        "imbalance_live": round(imb_live, 3),
        "imbalance_contiguous": round(imb_contig, 3),
        "assignment_contiguous": assignment.is_contiguous,
        "spill": engine.spill_counters(),
    }
    print(json.dumps(row))

    failures = []
    if responder.rebalances < 1 or responder.groups_moved < 1:
        failures.append(
            f"no live rebalance happened (rebalances="
            f"{responder.rebalances}, groups_moved="
            f"{responder.groups_moved})")
    if assignment.is_contiguous:
        failures.append("assignment is still contiguous — the moves "
                        "never reached the engine")
    if responder.keys_split < 1 or HOT not in stats["keys"]:
        failures.append(
            f"the dominant key was never split (keys_split="
            f"{responder.keys_split}, registry={stats['keys']})")
    if stats["salted_records"] == 0 or stats["salted_fires"] == 0:
        failures.append(
            f"two-stage aggregation never engaged (salted_records="
            f"{stats['salted_records']}, salted_fires="
            f"{stats['salted_fires']}) — vacuous")
    if imb_live >= imb_contig:
        failures.append(
            f"moves did not improve imbalance: live {imb_live:.3f} vs "
            f"contiguous {imb_contig:.3f}")
    if set(got) != set(expected):
        failures.append(
            f"window sets differ from the oracle: {len(got)} vs "
            f"{len(expected)}")
    elif got != expected:
        diverged = sum(1 for k in expected if got[k] != expected[k])
        failures.append(
            f"{diverged} windows diverged from the oracle (moves or "
            "salting leaked into the output)")
    if recovery < RECOVERY_BUDGET:
        failures.append(
            f"skewed throughput recovered only {recovery:.2f}x of the "
            f"uniform control (budget {RECOVERY_BUDGET})")
    if failures:
        print("SKEW SMOKE FAILED: " + "; ".join(failures),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
