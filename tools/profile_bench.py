"""Profile the Q5 bench hot loop (run on the real backend).

Usage: python tools/profile_bench.py [records]
Prints top cumulative-time functions to stderr.
"""
import cProfile
import io
import pstats
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("BENCH_SKIP_PROBE", "1")

from flink_tpu.platform import sync_platform

sync_platform()

from bench import run


def main():
    total = int(sys.argv[1]) if len(sys.argv) > 1 else 4_000_000
    # warmup (compiles everything)
    run(total_records=1 << 21, num_auctions=100_000)
    prof = cProfile.Profile()
    prof.enable()
    stats = run(total_records=total)
    prof.disable()
    print(f"events_per_s={stats['events_per_s']:.0f} "
          f"fire={stats['fire_latency_ms']}", file=sys.stderr)
    s = io.StringIO()
    ps = pstats.Stats(prof, stream=s).sort_stats("cumulative")
    ps.print_stats(45)
    print(s.getvalue(), file=sys.stderr)


if __name__ == "__main__":
    main()
