"""CEP benchmark: the ``cep_patterns_10m_keys`` row.

The row-5 thrashing shape applied to pattern detection: a 2-stage
within-window sequence over 10M distinct keys at 400k ev/s of event
time, so the live partial-match set (~260k keys holding a stage-a
partial inside the 2 s window) sits far above the per-shard device
budget — ingest evicts page cohorts and due keys reload (with the lazy
within-prune) straight from the paged tier.

The same shape runs on the HOST backend (the per-key ``CepOperator``
NFA — the bit-identity oracle every CEP gate diffs against) at a
reduced record count, and the row reports the device/host events-per-
second ratio. ``BENCH_CEP_REQUIRE_WIN=1`` makes a device loss a hard
error; ``BENCH_CEP_REQUIRE_SPILL=1`` fails a run where the spill tier
never engaged (a vacuous-coverage run must not publish a number).

Methodology matches bench.py: median of post-warm reps (best/all reps
as secondary fields). ``fire_latency_ms`` is the emit-latency
percentile set — wall time from a watermark advance to its matches
materialized on the host (the CEP analogue of window fire latency, so
the matrix stays comparable).

    BENCH_CEP_RECORDS=... BENCH_CEP_REPS=... \
        JAX_PLATFORMS=cpu python tools/bench_cep.py
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

from flink_tpu.metrics.core import quantile_sorted  # noqa: E402

BATCH = 1 << 15
NUM_KEYS = 10_000_000
RATE = 400_000          # events/s of event time
WITHIN_MS = 2_000
WM_LAG_MS = 500
BUDGET = 1 << 14        # slots/shard vs ~260k live partial keys


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def _latency(samples_ms):
    if not samples_ms:
        return None
    samples_ms = sorted(samples_ms)
    return {"p50": quantile_sorted(samples_ms, 0.5),
            "p99": quantile_sorted(samples_ms, 0.99),
            "max": samples_ms[-1], "count": len(samples_ms)}


def _pattern():
    from flink_tpu.cep.pattern import (
        AfterMatchSkipStrategy,
        Pattern,
    )

    return (Pattern.begin(
                "a", skip=AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT)
            .where(lambda b: np.asarray(b["v"]) % 3 == 0)
            .next("b")
            .where(lambda b: np.asarray(b["v"]) % 3 == 1)
            .within(WITHIN_MS))


def _drive(engine, total, seed):
    """Keyed batches at RATE ev/s of event time, a trailing-watermark
    fire after every batch, and a final drain fire. Returns (events,
    matches, emit-latency samples, wall seconds, breakdown) with the
    breakdown rolled up from this pass's flight-recorder spans (the
    shared ``observe.export.span_rollup`` — same primitive as the
    session and join rows, so the matrix attributes time the same
    way everywhere)."""
    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )
    from flink_tpu.observe import flight_recorder as flight

    rec = flight.recorder()
    flight.set_job("bench_cep")
    rec.clear()
    rng = np.random.default_rng(seed)
    events = matches = 0
    lat = []
    t0 = time.perf_counter()
    t = 0
    while events < total:
        n = min(BATCH, total - events)
        keys = rng.integers(0, NUM_KEYS, n).astype(np.int64)
        vals = rng.integers(0, 9, n).astype(np.int64)
        ts = t + (np.arange(n, dtype=np.int64) * 1000) // RATE
        engine.process_batch(RecordBatch({
            KEY_ID_FIELD: keys,
            "v": vals,
            TIMESTAMP_FIELD: ts,
        }))
        events += n
        t = int(ts[-1]) + 1
        f0 = time.perf_counter()
        out = engine.on_watermark(t - WM_LAG_MS)
        m = sum(len(b) for b in out)
        if m:
            lat.append((time.perf_counter() - f0) * 1e3)
        matches += m
    # staged drain: every fire must fit its due-key set inside the
    # per-shard slot budget, so the final watermark advances in
    # batch-sized steps instead of one MAX jump over the whole lag
    wm = t - WM_LAG_MS
    step = max(BATCH * 1000 // RATE, 1)
    while wm < t:
        wm = min(wm + step, t)
        matches += sum(len(b) for b in engine.on_watermark(wm))
    dt = time.perf_counter() - t0
    from flink_tpu.observe.export import span_rollup

    # the CEP engine emits ingest/fire/harvest spans but no
    # device.dispatch/fence pair (yet), so — like the join row — no
    # host_prep_s line: report only what the spans attribute
    breakdown = span_rollup(rec.kind_totals(), dt, {
        "ingest_s": "batch.ingest",
        "advance_fire_s": "fire.dispatch",
        "harvest_s": "fire.harvest",
    })
    return events, matches, lat, dt, breakdown


def bench_cep(scale=1.0, reps=None):
    from flink_tpu.cep.mesh_engine import MeshCepEngine

    total = int(int(os.environ.get(
        "BENCH_CEP_RECORDS", 4_000_000)) * scale)
    reps = reps or int(os.environ.get("BENCH_CEP_REPS", 3))

    def _mesh():
        import jax

        from flink_tpu.parallel.mesh import make_mesh

        return make_mesh(min(len(jax.devices()), 8))

    def make(spill_dir):
        return MeshCepEngine(_pattern(), mesh=_mesh(),
                             capacity_per_shard=BUDGET,
                             spill_dir=spill_dir)

    with tempfile.TemporaryDirectory() as td:
        _drive(make(td), min(total, 1 << 19), seed=3)  # warm
        runs = []
        spills = []
        for _ in range(reps):
            eng = make(td)
            runs.append(_drive(eng, total, seed=3))
            spills.append(eng.spill_counters())
    evps = [ev / dt for ev, _, _, dt, _ in runs]
    i = evps.index(_median(evps))
    ev, matches, lat, dt, breakdown = runs[i]
    sp = spills[i]
    if matches == 0:
        raise RuntimeError("vacuous cep bench: zero matches")
    if os.environ.get("BENCH_CEP_REQUIRE_SPILL") == "1" and (
            sp["rows_evicted"] == 0 or sp["rows_reloaded"] == 0):
        raise RuntimeError(
            f"vacuous cep bench: spill never engaged ({sp})")

    # the SAME shape on the host oracle (reduced record count — the
    # per-key python NFA is the thing being beaten, not raced at 4M)
    host_total = min(total, 1 << 18)
    host = MeshCepEngine(_pattern(), backend="host")
    hev, hmatches, _, hdt, _ = _drive(host, host_total, seed=3)
    host_evps = hev / hdt
    if hmatches == 0:
        raise RuntimeError("vacuous cep bench: host oracle emitted "
                           "zero matches")
    speedup = _median(evps) / host_evps
    if os.environ.get("BENCH_CEP_REQUIRE_WIN") == "1" and speedup <= 1:
        raise RuntimeError(
            f"device CEP did not beat the host oracle: "
            f"{_median(evps):,.0f} ev/s vs {host_evps:,.0f} ev/s")

    return {
        "metric": "cep_patterns_10m_keys_events_per_sec",
        "value": round(_median(evps), 1),
        "best": round(max(evps), 1),
        "reps": [round(x, 1) for x in evps],
        "unit": "events/s",
        "matches": int(matches),
        "fire_latency_ms": _latency(lat),
        "breakdown": breakdown,
        "spill": sp,
        "host_events_per_s": round(host_evps, 1),
        "speedup_vs_host": round(speedup, 2),
        "shape": (f"2-stage within-{WITHIN_MS // 1000}s sequence, "
                  f"10M distinct keys at {RATE:,} ev/s of event time "
                  f"(~260k live partials vs {BUDGET * 8:,} device "
                  f"slots) — forced paged eviction with lazy "
                  f"within-prune on reload; device NFA "
                  f"{speedup:.1f}x the host CepOperator oracle "
                  f"({host_evps:,.0f} ev/s) at the same shape"),
    }


def main():
    import warnings

    warnings.filterwarnings("ignore")
    # BENCH_CEP_RECORDS is the one scale knob — the suite driver folds
    # BENCH_SUITE_SCALE into it (the bench_mesh_sessions contract)
    print(json.dumps(bench_cep(1.0)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
