"""Multi-process serving bench: N shm frontends vs the 1-process path.

The BENCHMARKS.md row for the frontend tier (ISSUE r21): frontend
processes attach the owner's shm hot-cache arenas and run the probe →
packed-reply loop ENTIRELY in their own process, while the owner keeps
priming generation after generation at the publish cadence — so the
recorded number describes a plane that serves FRESH boundaries, not a
frozen table (the same staleness discipline the serving smoke gates).

Measured per run:

- ``serving_mp_lookups_per_s`` — aggregate shm lookups/s across all
  frontends (each frontend self-drives 256-key probe batches; counters
  come from the SHARED arena header via ``fe_stats``, not wall-clock
  division),
- the same loop single-process (``get_many_packed`` owner-side) for
  the scaling context,
- hit rate, torn retries, and the live-priming generation count
  (vacuity: a bench against a table nobody primes is a different
  product).

On a multi-core box the aggregate scales with frontends (the ISSUE
target: >= 3M lookups/s); a 1-core CI box time-shares the clock and
records the protocol overhead instead — the smoke
(tools/frontend_smoke.py) carries the structural guarantees there.

    JAX_PLATFORMS=cpu python tools/bench_serving_mp.py
    BENCH_SERVING_MP_FRONTENDS=N  BENCH_SERVING_MP_BATCHES=M to scale.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

KEYS = int(os.environ.get("BENCH_SERVING_MP_KEYS", 4096))
BATCH = int(os.environ.get("BENCH_SERVING_MP_BATCH", 256))
BATCHES = int(os.environ.get("BENCH_SERVING_MP_BATCHES", 2000))
FRONTENDS = int(os.environ.get(
    "BENCH_SERVING_MP_FRONTENDS",
    str(max(2, min(4, len(os.sched_getaffinity(0)))))))
PRIME_INTERVAL_MS = float(os.environ.get(
    "BENCH_SERVING_MP_PRIME_INTERVAL_MS", 25.0))
JOB, OP = "bench", "window_agg"


class _BenchPlane:
    """The minimal owner the pool needs: the shm cache + a dict-oracle
    miss resolver (the bench pre-primes, so misses are signal)."""

    def __init__(self, cache):
        self.hot_cache = cache

    def lookup_batch(self, job, op, keys):
        return [{"cold": float(k)} for k in keys]


def _values(keys, gen):
    return [{0: {"g": float(gen), "v": gen * 1_000_000.0 + float(k)}}
            for k in keys]


def main():
    import tempfile

    import numpy as np

    from flink_tpu.native import hotcache_available

    if not hotcache_available():
        print("BENCH SERVING MP: native hotcache unavailable")
        return 1
    from flink_tpu.tenancy.frontend import FrontendPool
    from flink_tpu.tenancy.hot_cache import make_hot_row_cache

    with tempfile.TemporaryDirectory(prefix="bench_mp_") as tmp:
        cache = make_hot_row_cache(max_entries=1 << 18,
                                   shm_dir=os.path.join(tmp, "shm"))
        try:
            keys = list(range(KEYS))
            cache.put_many(JOB, OP, keys, 1, _values(keys, 1))

            # ---- single-process reference: the owner's own packed
            # probe loop, same batch shape (the r19 fast path)
            kid = np.arange(KEYS, dtype=np.int64)
            t0 = time.perf_counter()
            for b in range(BATCHES):
                lo = (b * BATCH) % (KEYS - BATCH + 1)
                out = [None] * BATCH
                misses = []
                cache.get_many_packed(JOB, OP, kid[lo:lo + BATCH], 1,
                                      out, misses, exact=False)
            single_wall = time.perf_counter() - t0
            single_per_s = BATCHES * BATCH / single_wall

            # ---- multi-process: N frontends drive the same loop in
            # their own processes while the owner keeps PRIMING at the
            # publish cadence (fresh boundaries under the probes)
            pool = FrontendPool(_BenchPlane(cache),
                                n_frontends=FRONTENDS)
            # children pay interpreter+import boot before their first
            # recv — gate on readiness so t0 measures probing, not boot
            pool.wait_ready()
            stop = threading.Event()
            primed = {"gens": 1}

            def primer():
                gen = 1
                while not stop.is_set():
                    gen += 1
                    cache.put_many(JOB, OP, keys, gen,
                                   _values(keys, gen))
                    primed["gens"] = gen
                    time.sleep(PRIME_INTERVAL_MS / 1e3)

            th = threading.Thread(target=primer, daemon=True)
            th.start()
            try:
                t0 = time.perf_counter()
                reports = pool.drive(JOB, OP, keys, batch=BATCH,
                                     batches=BATCHES)
                mp_wall = time.perf_counter() - t0
            finally:
                stop.set()
                th.join(timeout=5)
                fe_rows = cache.fe_stats(FRONTENDS)
                pool.close()
            # REAL counters off the shared header, not wall division
            probes = sum(r["probes"] for r in fe_rows)
            hits = sum(r["hits"] for r in fe_rows)
            torn = sum(r["torn_retries"] for r in fe_rows)
            mp_per_s = probes / mp_wall if mp_wall > 0 else 0.0
            hit_rate = hits / probes if probes else 0.0
            ok = True
            if len(reports) < FRONTENDS:
                print(f"FAIL: only {len(reports)}/{FRONTENDS} "
                      "frontends reported")
                ok = False
            if hit_rate < 0.98:
                print(f"FAIL: hit rate {hit_rate:.3f} — vacuous bench "
                      "(the table must serve)")
                ok = False
            if primed["gens"] < 3:
                print(f"FAIL: owner primed only {primed['gens']} "
                      "generations — the bench ran against a frozen "
                      "table")
                ok = False
            from flink_tpu.tenancy.serving import (
                aggregate_lookup_stats,
            )

            stats = aggregate_lookup_stats([], frontend_stats=fe_rows)
            print(json.dumps({
                "metric": "serving_mp_lookups_per_s",
                "value": round(mp_per_s, 1),
                "unit": "lookups/s aggregate",
                "shape": (
                    f"{FRONTENDS} frontend processes x {BATCHES} "
                    f"{BATCH}-key shm probe batches against one "
                    f"owner-primed arena ({KEYS} keys, 2 cols), owner "
                    f"priming every {PRIME_INTERVAL_MS:.0f} ms "
                    f"({primed['gens']} generations live under the "
                    f"probes): hit rate {hit_rate:.3f}, "
                    f"{torn} torn retries (0 surfaced), 1-process "
                    f"packed path {single_per_s:,.0f}/s same box -> "
                    f"scaling {mp_per_s / single_per_s:.2f}x"),
                "single_proc_lookups_per_s": round(single_per_s, 1),
                "scaling_x": round(mp_per_s / single_per_s, 2),
                "frontend_stats": stats,
                "per_frontend": reports,
            }), flush=True)
            print(f"bench serving mp: {mp_per_s:,.0f} lookups/s over "
                  f"{FRONTENDS} frontends (1-proc {single_per_s:,.0f}; "
                  f"hit_rate={hit_rate:.3f} torn_retries={torn} "
                  f"generations={primed['gens']}) => "
                  f"{'OK' if ok else 'FAIL'}")
            return 0 if ok else 1
        finally:
            cache.close()


if __name__ == "__main__":
    sys.exit(main())
